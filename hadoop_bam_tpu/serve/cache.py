"""Header/index cache for the resident daemon, keyed by file identity.

Cold-start batch re-reads the BAM header and any `.bai`/`.tbi`/
`.splitting-bai` on every job; a long-lived server must not.  Entries are
keyed by ``(path, size, mtime_ns)`` *file identity* — a rewritten or
touched file is a different key, so staleness is detected at lookup time
(the entry is dropped and reloaded) rather than by TTL guesswork.  The
cache is LRU under a byte budget, and every lookup lands in METRICS
(``serve.cache.{hit,miss,stale,evict}`` plus a per-kind itemization) so
the daemon's ``stats`` endpoint and per-request deltas show real hit
rates, not inferences.
"""

from __future__ import annotations

import io
import os
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional, Tuple

from ..utils.tracing import METRICS

#: ``(path, size, mtime_ns)`` — the staleness key (the same identity rule
#: the splitting-bai planner uses via its ``bam_size()`` terminator check,
#: extended with mtime so an in-place rewrite of equal size still misses).
FileIdentity = Tuple[str, int, int]


def file_identity(path: str) -> FileIdentity:
    st = os.stat(path)
    return (path, st.st_size, st.st_mtime_ns)


class _Flight:
    """One in-flight load: waiters block on ``done`` and read
    ``value``/``err`` — the stampede-dedup rendezvous."""

    __slots__ = ("done", "value", "err")

    def __init__(self) -> None:
        self.done = threading.Event()
        self.value: Any = None
        self.err: Optional[BaseException] = None


class LruByteCache:
    """Thread-safe identity-validating LRU cache under a byte budget."""

    def __init__(self, budget_bytes: int = 256 << 20, name: str = "serve.cache"):
        if budget_bytes < 1:
            raise ValueError("budget_bytes must be >= 1")
        self.budget = budget_bytes
        self.name = name
        self._lock = threading.Lock()
        # (kind, path) -> (identity, nbytes, value); insertion order = LRU.
        self._entries: "OrderedDict[Tuple[str, str], Tuple[FileIdentity, int, Any]]" = (
            OrderedDict()
        )
        self.used_bytes = 0
        # Per-key in-flight loads (stampede dedup): one loader runs per
        # (kind, path) at a time; concurrent misses wait and share.
        self._inflight_lock = threading.Lock()
        self._inflight: dict = {}

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, kind: str, path: str, identity: Optional[FileIdentity] = None):
        """The cached value, or None on miss.  A changed file identity
        (size or mtime moved) invalidates the entry — counted ``stale``
        on top of the miss, so silent-corruption risks are visible."""
        if identity is None:
            try:
                identity = file_identity(path)
            except OSError:
                identity = None  # vanished file: any entry is stale
        key = (kind, path)
        with self._lock:
            e = self._entries.get(key)
            if e is not None and identity is not None and e[0] == identity:
                self._entries.move_to_end(key)
                METRICS.count(f"{self.name}.hit", 1)
                METRICS.count(f"{self.name}.hit.{kind}", 1)
                return e[2]
            if e is not None:
                # Present but wrong identity: drop it now (a later put
                # would overwrite anyway, but eviction accounting should
                # not carry dead bytes meanwhile).
                self.used_bytes -= e[1]
                del self._entries[key]
                METRICS.count(f"{self.name}.stale", 1)
        METRICS.count(f"{self.name}.miss", 1)
        METRICS.count(f"{self.name}.miss.{kind}", 1)
        return None

    def put(
        self,
        kind: str,
        path: str,
        value: Any,
        nbytes: int,
        identity: Optional[FileIdentity] = None,
    ) -> None:
        if identity is None:
            identity = file_identity(path)
        key = (kind, path)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.used_bytes -= old[1]
            self._entries[key] = (identity, int(nbytes), value)
            self.used_bytes += int(nbytes)
            # Evict LRU down to budget; the entry just inserted survives
            # even when it alone exceeds the budget (callers cached it for
            # a reason — it just pins the whole budget until displaced).
            while self.used_bytes > self.budget and len(self._entries) > 1:
                _, (_, nb, _) = self._entries.popitem(last=False)
                self.used_bytes -= nb
                METRICS.count(f"{self.name}.evict", 1)

    def get_or_load(
        self,
        kind: str,
        path: str,
        loader: Callable[[str], Any],
        sizer: Callable[[Any], int],
    ):
        """get() falling through to ``loader(path)`` + put().

        The load runs outside the cache lock (loads can be slow I/O) but
        is **deduplicated per key**: concurrent misses on the same
        ``(kind, path)`` used to each run the loader (a cache stampede —
        N clients hitting a cold index paid N full index reads); now the
        first miss is the leader, the rest wait on its completion event
        and share the result (``serve.cache.stampede_wait`` counts the
        waiters).  A failing load propagates its exception to every
        waiter of that flight; the next request starts a fresh flight.
        """
        ident = file_identity(path)
        v = self.get(kind, path, identity=ident)
        if v is not None:
            return v
        key = (kind, path)
        with self._inflight_lock:
            flight = self._inflight.get(key)
            leader = flight is None
            if leader:
                flight = self._inflight[key] = _Flight()
        if not leader:
            METRICS.count(f"{self.name}.stampede_wait", 1)
            flight.done.wait()
            if flight.err is not None:
                raise flight.err
            return flight.value
        try:
            # Identity re-read under leadership: the file may have been
            # rewritten between our miss and winning the flight.
            ident = file_identity(path)
            v = loader(path)
            self.put(kind, path, v, sizer(v), identity=ident)
            flight.value = v
            return v
        except BaseException as e:
            flight.err = e
            raise
        finally:
            with self._inflight_lock:
                self._inflight.pop(key, None)
            flight.done.set()

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "used_bytes": self.used_bytes,
                "budget_bytes": self.budget,
            }


def _sizeof_saveable(obj) -> int:
    """Exact serialized size of an index object exposing ``save(stream)``
    — cheap at header/index scale and honest for the byte budget."""
    buf = io.BytesIO()
    obj.save(buf)
    return buf.tell()


class ResourceCache:
    """The daemon's header + index cache: BAM headers, `.bai`, `.tbi`,
    `.splitting-bai`, each validated by file identity on every lookup.

    A warm ``view`` request must trigger zero header/index re-reads —
    that claim is the ``serve.cache.miss`` delta being zero, asserted in
    tests/test_serve.py rather than assumed.
    """

    def __init__(self, budget_bytes: int = 256 << 20):
        self.lru = LruByteCache(budget_bytes, name="serve.cache")

    def identity(self, path: str) -> FileIdentity:
        return file_identity(path)

    def header(self, path: str):
        """(BamHeader, first-record virtual offset) for a BAM path —
        or a CRAM path, whose header comes from the file-header
        container (virtual offset 0: CRAM addressing is container-based,
        not BGZF-virtual)."""
        from ..io.anysam import infer_from_file_path
        from ..io.bam import read_header_voffset

        def load(p: str):
            if infer_from_file_path(p) == "cram":
                from ..io.cram import read_cram_header

                return read_cram_header(p), 0
            return read_header_voffset(p)

        def size(v) -> int:
            hdr = v[0]
            return len(hdr.text) + sum(len(n) + 16 for n, _ in hdr.refs) + 64

        return self.lru.get_or_load("header", path, load, size)

    def bai(self, path: str):
        """The `.bai` for a BAM path — the companion file when present
        (htsjdk naming convention), else derived by walking the BAM.

        The cache key follows the *source actually read*: a companion
        `.bai` entry invalidates when the index file changes; a derived
        entry invalidates when the BAM itself does.
        """
        from ..io.bam import _find_bai
        from ..io import fs
        from ..spec import indices

        bai_path = _find_bai(path)
        if bai_path is not None:
            return self.lru.get_or_load(
                "bai",
                bai_path,
                lambda p: indices.Bai.load(fs.get_fs(p).read_all(p)),
                _sizeof_saveable,
            )
        return self.lru.get_or_load(
            "bai-derived",
            path,
            lambda p: indices.build_bai(fs.get_fs(p).read_all(p)),
            _sizeof_saveable,
        )

    def splitting_bai(self, path: str):
        """The `.splitting-bai` companion, or None when absent."""
        from ..io import fs
        from ..spec import indices

        idx_path = path + indices.SPLITTING_BAI_EXT
        if not fs.get_fs(idx_path).exists(idx_path):
            return None
        return self.lru.get_or_load(
            "splitting-bai",
            idx_path,
            lambda p: indices.SplittingBai.load(fs.get_fs(p).read_all(p)),
            lambda v: 8 * v.size(),
        )

    def tabix(self, path: str):
        """The `.tbi` companion of a tabix-indexed file, or None."""
        from ..io import fs
        from ..spec import indices

        tbi_path = path + ".tbi"
        if not fs.get_fs(tbi_path).exists(tbi_path):
            return None
        return self.lru.get_or_load(
            "tbi",
            tbi_path,
            lambda p: indices.Tabix.load(fs.get_fs(p).read_all(p)),
            lambda v: sum(
                16 * sum(len(c) for c in r.bins.values()) + 8 * len(r.linear)
                for r in v.refs
            )
            + 64,
        )

    def bcf_plan(self, path: str):
        """(BcfHeader, record-start splits) for a BCF path — the variant
        plane's index analogue.  Split planning walks the file once with
        the guesser (the cold cost the reference's BCFSplitGuesser pays
        too), so caching the plan under the file identity makes warm
        region queries plan-free; a rewritten file re-plans via the
        (path, size, mtime_ns) key like every other cached resource."""
        from ..conf import Configuration
        from ..io.bcf import BcfInputFormat, _read_bcf_header_prefix

        def load(p: str):
            hdr, _ = _read_bcf_header_prefix(p)
            splits = BcfInputFormat(Configuration()).get_splits([p])
            return hdr, splits

        def size(v) -> int:
            hdr, splits = v
            return 4096 + sum(len(c) + 16 for c in hdr.contigs) + 80 * len(
                splits
            )

        return self.lru.get_or_load("bcf-plan", path, load, size)

    def stats(self) -> dict:
        return self.lru.stats()
