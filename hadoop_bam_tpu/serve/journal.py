"""Crash-safe job journal: append-only JSONL of submissions + transitions.

A daemon crash used to lose every queued and running job silently: a
client polling ``wait_job`` against the restarted daemon got "unknown
job id" forever, and an interrupted sort's partial work was orphaned.
The journal closes that gap with the spill manifest's durability stance
(io/runs.py): every submission and state transition is one JSON line,
appended with flush + ``fsync`` before the daemon acts on it, so the
on-disk journal is never *behind* the daemon's observable behavior.

Replay on restart:

- **terminal jobs** (``done``/``failed``) are restored verbatim — a
  restarted daemon reports accurate terminal states instead of amnesia;
- **interrupted jobs** (submitted/running at the crash) are *resumable*
  when their recorded input identity (``(path, size, mtime_ns)``, the
  serve-cache/spill-manifest rule) still matches and the request named a
  persistent ``part_dir`` — the rerun rides the PR 7 spill-manifest +
  validated-part resume path, reproducing the uninterrupted output
  byte-identically;
- anything else is marked **lost** (with a reason) — the client's
  ``wait_job`` surfaces a typed ``JOB_LOST`` instead of polling forever.

A torn final line (the crash landed mid-append) is detected and dropped
(``serve.journal.torn_tail``); a stale journal — entries whose input
identity no longer matches the files on disk — is never trusted to
resume (``serve.journal.stale``).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

from ..utils.tracing import METRICS

#: Journal format version; replay rejects lines from a different one.
VERSION = 1

#: Job states that need no recovery action on replay.
TERMINAL_STATES = frozenset(("done", "failed", "lost"))


def input_identity(paths: List[str]) -> Optional[List[Dict]]:
    """``(path, size, mtime_ns)`` fingerprints of a job's inputs, or
    None when any cannot be stat'd (non-local inputs: no resume)."""
    out: List[Dict] = []
    try:
        for p in paths:
            st = os.stat(p)
            out.append(
                {"path": p, "size": st.st_size, "mtime_ns": st.st_mtime_ns}
            )
    except OSError:
        return None
    return out


def identity_current(inputs: Optional[List[Dict]]) -> bool:
    """Do the recorded input fingerprints still match the files on disk?
    A journal recorded against different bytes must never seed a resume
    (the spill manifest applies the same rule independently)."""
    if not inputs:
        return False
    try:
        for e in inputs:
            st = os.stat(e["path"])
            if (
                st.st_size != e["size"]
                or st.st_mtime_ns != e["mtime_ns"]
            ):
                return False
    except OSError:
        return False
    return True


class JobJournal:
    """Append-only JSONL journal with fsync'd appends (thread-safe)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = None

    def open(self) -> None:
        if self._f is None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            self._f = open(self.path, "ab")

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                finally:
                    self._f = None

    def append(self, event: dict) -> None:
        """One journal line, durable before return: a state the daemon
        acts on is on disk first (write + flush + fsync — the same
        torn-write stance as the spill manifest's atomic replace)."""
        self.open()
        line = (
            json.dumps(
                {"v": VERSION, **event}, separators=(",", ":")
            ).encode("utf-8")
            + b"\n"
        )
        with self._lock:
            self._f.write(line)
            self._f.flush()
            os.fsync(self._f.fileno())
        METRICS.count("serve.journal.appends", 1)

    def submit(self, jid: str, req: dict, inputs: Optional[List[Dict]]) -> None:
        self.append(
            {"event": "submit", "job": jid, "req": req, "inputs": inputs}
        )

    def state(self, jid: str, status: str, **extra) -> None:
        self.append({"event": "state", "job": jid, "status": status, **extra})


def replay(path: str) -> Dict[str, dict]:
    """Reconstruct job states from a journal file.

    Returns ``{jid: {"status", "req", "inputs", ...}}`` where ``status``
    is the last recorded one (``submitted`` if only the submission ever
    landed).  Unparseable *trailing* data — the torn final append of a
    crash — is dropped and counted; an unparseable line in the middle
    fails the whole replay (that is corruption, not a torn tail).
    """
    jobs: Dict[str, dict] = {}
    if not os.path.exists(path):
        return jobs
    with open(path, "rb") as f:
        raw = f.read()
    lines = raw.split(b"\n")
    # A well-formed journal ends with a newline → last element is empty.
    torn = lines[-1] != b""
    body = lines[:-1]
    for i, line in enumerate(body):
        if not line:
            continue
        try:
            ev = json.loads(line.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            if i == len(body) - 1 and not torn:
                torn = True  # torn mid-line then truncated at a newline
                break
            raise ValueError(
                f"corrupt journal line {i} in {path!r}"
            ) from None
        if ev.get("v") != VERSION:
            raise ValueError(
                f"journal {path!r} has version {ev.get('v')!r}, "
                f"expected {VERSION}"
            )
        jid = ev.get("job")
        if ev.get("event") == "submit":
            jobs[jid] = {
                "status": "submitted",
                "req": ev.get("req") or {},
                "inputs": ev.get("inputs"),
            }
        elif ev.get("event") == "state" and jid in jobs:
            jobs[jid]["status"] = ev.get("status")
            for k in ("stats", "error", "output"):
                if k in ev:
                    jobs[jid][k] = ev[k]
    if torn:
        METRICS.count("serve.journal.torn_tail", 1)
    return jobs


def recovery_plan(jobs: Dict[str, dict]) -> Dict[str, str]:
    """Per interrupted job, the recovery action: ``resume`` (inputs
    identity still matches and the request carries a persistent
    ``part_dir`` — the PR 7 checkpoints make the rerun byte-identical)
    or ``lost`` (anything the daemon cannot honestly re-run).  Terminal
    jobs need no action and are absent."""
    plan: Dict[str, str] = {}
    for jid, job in jobs.items():
        if job["status"] in TERMINAL_STATES:
            continue
        req = job.get("req") or {}
        if not identity_current(job.get("inputs")):
            plan[jid] = "lost"
            METRICS.count("serve.journal.stale", 1)
        elif req.get("part_dir"):
            plan[jid] = "resume"
        else:
            plan[jid] = "lost"
    return plan
