"""Resident service mode: the pipeline as a long-lived TPU daemon.

Cold-start batch (``python -m hadoop_bam_tpu sort …``) re-imports JAX,
re-compiles every kernel geometry and re-reads headers/indices per job;
this package keeps all of that warm in one process that owns the TPU and
serves a stream of requests over a localhost/UDS socket (ROADMAP open
item 4 — the Sam2bam "keep the accelerator resident" stance, with
admitted requests overlapping in-flight device work):

- :mod:`~hadoop_bam_tpu.serve.server` — accept loop, request dispatch,
  bounded job pool, graceful drain;
- :mod:`~hadoop_bam_tpu.serve.client` — the thin stdlib client;
- :mod:`~hadoop_bam_tpu.serve.warmup` — startup pre-compilation of the
  pow2 kernel geometry buckets + the XLA compile counter;
- :mod:`~hadoop_bam_tpu.serve.cache` — header/index LRU keyed by
  ``(path, size, mtime)`` file identity;
- :mod:`~hadoop_bam_tpu.serve.arena` — the warm HBM residency arena
  (decoded split windows, device payloads included, reused across
  requests);
- :mod:`~hadoop_bam_tpu.serve.batching` — the admission queue packing
  concurrent small requests' member inflates into shared 128-lane
  launches;
- :mod:`~hadoop_bam_tpu.serve.endpoints` — ``view`` / ``flagstat``
  implementations shared byte-for-byte with the one-shot CLI
  subcommands;
- :mod:`~hadoop_bam_tpu.serve.fleet` + :mod:`~hadoop_bam_tpu.serve.router`
  — N daemons behind one stdlib front router: consistent-hash placement
  on the cache file identity, federated admission, heartbeat membership,
  and journal adoption on an unclean death (PR 18).
"""

from .admission import (
    ERROR_CODES,
    AdmissionController,
    FleetLedger,
    ShedError,
)
from .arena import HbmArena
from .batching import LaneBatcher
from .cache import LruByteCache, ResourceCache, file_identity
from .client import (
    DeadlineExceededError,
    JobLostError,
    ServeClient,
    ServeError,
    ServeShedError,
)
from .endpoints import ServeContext, flagstat, view_blob, view_records
from .exemplars import ExemplarStore, TailSampler
from .fleet import HashRing, Heartbeater, classify_death, file_key
from .flightrec import AccessLog
from .journal import JobJournal
from .router import FleetRouter, default_router_socket_path
from .server import BamDaemon, default_socket_path
from .slo import SloMonitor, SloObjective, fold_slo, parse_objectives
from .warmup import compile_count, ensure_compile_watcher, warm_kernels

__all__ = [
    "AccessLog",
    "AdmissionController",
    "BamDaemon",
    "ExemplarStore",
    "FleetLedger",
    "FleetRouter",
    "HashRing",
    "Heartbeater",
    "SloMonitor",
    "SloObjective",
    "TailSampler",
    "classify_death",
    "fold_slo",
    "parse_objectives",
    "DeadlineExceededError",
    "ERROR_CODES",
    "HbmArena",
    "JobJournal",
    "JobLostError",
    "LaneBatcher",
    "LruByteCache",
    "ResourceCache",
    "ServeClient",
    "ServeContext",
    "ServeError",
    "ServeShedError",
    "ShedError",
    "compile_count",
    "default_router_socket_path",
    "default_socket_path",
    "ensure_compile_watcher",
    "file_identity",
    "file_key",
    "flagstat",
    "view_blob",
    "view_records",
    "warm_kernels",
]
