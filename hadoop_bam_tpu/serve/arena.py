"""Warm residency arena: decoded split windows kept across requests.

The batch pipeline frees every split's payload when its job ends; a
resident daemon answering high-QPS ranged ``view`` requests should not
re-read, re-inflate and re-decode the same window for every hit on a hot
region.  The arena holds decoded :class:`~hadoop_bam_tpu.io.bam.RecordBatch`
windows — including their HBM-resident ``device_data`` when the
lockstep-lane inflate tier left one — keyed by ``(file identity, voffset
range, field set)``, LRU under a byte budget.  Dropping an entry releases
both the host buffer and the device buffer (jax frees HBM when the last
reference dies), so the budget bounds HBM residency too.

This is deliberately *content* residency, not raw buffer pooling: reusing
a decoded window skips the disk read, the inflate (host or device), the
chain walk and the SoA decode in one stroke, and the device-resident copy
rides along for the kernels that consume residency
(``pipeline._device_parse_split``, the device write path).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Hashable, Optional

from ..utils.hbm import LEDGER
from ..utils.tracing import METRICS


def _batch_nbytes(batch) -> int:
    """Budget charge of a held batch: payload + SoA columns (the device
    copy mirrors the payload bytes, so it is charged once — HBM and host
    budgets are tracked by the same number)."""
    n = len(batch.data)
    for col in batch.soa.values():
        n += getattr(col, "nbytes", 0)
    keys = getattr(batch, "keys", None)
    if keys is not None:
        n += getattr(keys, "nbytes", 0)
    return n


class HbmArena:
    """LRU residency arena under a byte budget (thread-safe)."""

    def __init__(
        self,
        budget_bytes: int = 1 << 30,
        name: str = "serve.arena",
        stream=None,
    ):
        if budget_bytes < 1:
            raise ValueError("budget_bytes must be >= 1")
        self.budget = budget_bytes
        self.name = name
        #: The daemon's DeviceStream, when the arena is a stream client:
        #: residency handoffs and drops ride the stream's ledger seam —
        #: one holder story instead of a parallel implementation.  A
        #: standalone arena (tests, host-only tools) talks to the
        #: process-global LEDGER directly, which is the same accounting.
        self.stream = stream
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, tuple]" = OrderedDict()
        self.used_bytes = 0
        METRICS.set_gauge(f"{self.name}.budget_bytes", budget_bytes)
        self._publish_gauges()

    def __len__(self) -> int:
        return len(self._entries)

    def _publish_gauges(self) -> None:
        """First-class occupancy gauges (``MetricsRegistry.set_gauge``):
        the serve ``metrics`` op exports these in Prometheus text
        without the server re-collecting arena numbers per scrape."""
        METRICS.set_gauge(f"{self.name}.used_bytes", self.used_bytes)
        METRICS.set_gauge(f"{self.name}.entries", len(self._entries))

    def _ledger_drop(self, batch) -> None:
        """Release a dropped window's HBM residency through the
        stream's ledger seam (HBM frees when the last reference dies;
        the ledger release is the audited bookkeeping event)."""
        dd = getattr(batch, "device_data", None)
        if dd is not None:
            (self.stream.release if self.stream is not None
             else LEDGER.release)(dd)

    def get(self, key: Hashable):
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                METRICS.count(f"{self.name}.miss", 1)
                return None
            self._entries.move_to_end(key)
            METRICS.count(f"{self.name}.hit", 1)
            return e[1]

    def hold(self, key: Hashable, batch, nbytes: Optional[int] = None) -> None:
        """Adopt a decoded window into the arena (replacing any previous
        entry under the key)."""
        nb = int(nbytes if nbytes is not None else _batch_nbytes(batch))
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.used_bytes -= old[0]
                if old[1] is not batch:
                    self._ledger_drop(old[1])
            self._entries[key] = (nb, batch)
            self.used_bytes += nb
            if getattr(batch, "device_data", None) is not None:
                METRICS.count(f"{self.name}.device_resident", 1)
                # Ownership handoff: the arena now holds the window's
                # HBM residency across requests (by design — excluded
                # from the end-of-run leak check).
                (self.stream.transfer if self.stream is not None
                 else LEDGER.transfer)(batch.device_data, self.name)
            while self.used_bytes > self.budget and len(self._entries) > 1:
                _, (nb_old, b_old) = self._entries.popitem(last=False)
                self.used_bytes -= nb_old
                self._ledger_drop(b_old)
                METRICS.count(f"{self.name}.evict", 1)
            self._publish_gauges()

    def keys(self) -> list:
        """Snapshot of the held keys, LRU→MRU (fleet warmth export and
        the report tooling walk this; the lock is not held across the
        caller's iteration)."""
        with self._lock:
            return list(self._entries.keys())

    def evict_stale(self, path: str, current_identity: tuple) -> int:
        """Drop every window decoded under a *previous* identity of
        ``path``: entries keyed ``(kind, (path, size, mtime_ns), ...)``
        whose identity tuple names this path but is not
        ``current_identity``.  The routed-daemon revalidation seam — a
        file rewritten in place (same path, new size/mtime_ns) must not
        serve yesterday's decoded windows.  Returns the number dropped;
        counts ``serve.cache.stale_evict`` per entry."""
        dropped = 0
        with self._lock:
            stale = [
                k
                for k in self._entries
                if isinstance(k, tuple)
                and len(k) >= 2
                and isinstance(k[1], tuple)
                and len(k[1]) == 3
                and k[1][0] == path
                and k[1] != current_identity
            ]
            for k in stale:
                nb, b_old = self._entries.pop(k)
                self.used_bytes -= nb
                self._ledger_drop(b_old)
                dropped += 1
            if dropped:
                self._publish_gauges()
        if dropped:
            METRICS.count("serve.cache.stale_evict", dropped)
        return dropped

    def evict_lru(self, n: int = 1) -> int:
        """Forcibly drop the ``n`` least-recently-used entries — the OOM
        recovery lever: on a device ``RESOURCE_EXHAUSTED`` the serve
        layer evicts residency (freeing HBM with the dropped references)
        and retries once before tiering the request down to the host
        path.  Returns how many entries were dropped (0 when empty);
        counts ``serve.oom.evictions`` per entry."""
        dropped = 0
        with self._lock:
            while self._entries and dropped < n:
                _, (nb, b_old) = self._entries.popitem(last=False)
                self.used_bytes -= nb
                self._ledger_drop(b_old)
                dropped += 1
            self._publish_gauges()
        if dropped:
            METRICS.count("serve.oom.evictions", dropped)
        return dropped

    def release_all(self) -> None:
        """Drop everything (daemon drain: HBM frees with the references)."""
        with self._lock:
            for _, b_old in self._entries.values():
                self._ledger_drop(b_old)
            self._entries.clear()
            self.used_bytes = 0
            self._publish_gauges()

    def stats(self) -> dict:
        with self._lock:
            device_resident = sum(
                1
                for _, b in self._entries.values()
                if getattr(b, "device_data", None) is not None
            )
            return {
                "entries": len(self._entries),
                "used_bytes": self.used_bytes,
                "budget_bytes": self.budget,
                "device_resident": device_resident,
            }
