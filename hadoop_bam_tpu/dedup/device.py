"""The on-device duplicate-marking decision.

Input: the fixed-width int32 signature columns of
:func:`dedup.signature.signature_columns` for the whole job.  Output: a
bool mask in original record order — True rows get ``FLAG_DUPLICATE``
ORed into their written flag bytes.

Everything is 32-bit (TPU-native lanes; no reliance on x64 mode) and
every ordering is made total by appending the original index as the last
sort key, so the result is deterministic and bit-identical to
:func:`dedup.oracle.mark_duplicates_oracle` regardless of platform.

Three passes, all ``lax.sort`` + segmented scatter reductions:

1. **Collation** — the name-collation engine's shared core
   (:func:`collate.device.collate_core`): sort pair candidates by the
   64-bit name hash with content tie-breaks; a segment of exactly two
   candidates is a mated pair and the two rows exchange end signature,
   score, and index by neighbor shift.  Because the core's tie-breaks
   are content-only (flag → 5′ position → index), the collation — and
   therefore the whole decision — accepts coordinate-sorted,
   queryname-grouped, or arbitrarily shuffled input identically
   (markdup-on-unsorted is this property, not a separate mode).
2. **Grouping** — sort everything by (own end signature, mated-first,
   mate end signature).  Rows with equal (self, mate) signature pairs are
   exactly the row-side views of duplicate pair families (both mates of a
   family land in consistent groups, so both sides elect the same
   winner); rows with equal self signature form the fragment families
   and see, via a segmented max, whether any mated pair shares their end.
3. **Election** — segmented lexicographic arg-max: pairs by summed pair
   score, fragments by own score; fragments lose outright to any pair
   sharing their end signature.  Ties break on record *content* (the
   64-bit name hash, then the flag word) before falling back to the
   original index, so the decision is independent of input order — and
   therefore idempotent: re-marking a marked, sorted file elects the
   same winners (``FLAG_DUPLICATE`` itself never enters the signature).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..collate.device import _prev, collate_core

_I32MAX = np.int32(2**31 - 1)


@jax.jit
def _mark_core(
    refid, pos5, rev, exempt, cand, score, qh1, qh2, flag
):
    n = refid.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    pos = jnp.arange(n, dtype=jnp.int32)
    zeros = jnp.zeros(n, jnp.int32)
    imax = jnp.full(n, _I32MAX, jnp.int32)

    def elect(seg, member, score_col, tie_cols):
        """True on each segment's winner rows: maximal ``score_col``,
        ties resolved by successive minima over ``tie_cols``."""
        best = zeros.at[seg].max(jnp.where(member, score_col, -1))[seg]
        sel = member & (score_col == best)
        for c in tie_cols:
            m = imax.at[seg].min(jnp.where(sel, c, _I32MAX))[seg]
            sel = sel & (c == m)
        return sel

    # ---- pass 1: name-hash collation of pair candidates ------------------
    # The shared engine core (collate/device.py): candidates grouped by
    # the 64-bit hash with content tie-breaks, a 2-candidate segment's
    # mates adjacent and exchanged through ``nb``.
    idxs, _, _, _, mated, nb = collate_core(
        cand, qh1, qh2, cand, flag, pos5
    )
    cands = cand[idxs]
    refids, pos5s, revs = refid[idxs], pos5[idxs], rev[idxs]
    exempts, scores, flags = exempt[idxs], score[idxs], flag[idxs]
    qh1s, qh2s = qh1[idxs], qh2[idxs]
    m_refid = jnp.where(mated, refids[nb], 0)
    m_pos5 = jnp.where(mated, pos5s[nb], 0)
    m_rev = jnp.where(mated, revs[nb], 0)
    pscore = jnp.where(mated, scores + scores[nb], 0)
    pidx = jnp.where(mated, jnp.minimum(idxs, idxs[nb]), 0)
    nmated = 1 - mated.astype(jnp.int32)

    # ---- pass 2: signature grouping --------------------------------------
    srt = lax.sort(
        (
            exempts, refids, pos5s, revs, nmated,
            m_refid, m_pos5, m_rev, idxs, pos,
        ),
        num_keys=9,
    )
    p2 = srt[9]
    refid3, pos53, rev3 = refids[p2], pos5s[p2], revs[p2]
    ex3 = exempts[p2].astype(bool)
    mated3 = mated[p2]
    idx3, score3 = idxs[p2], scores[p2]
    qh1_3, qh2_3, flag3 = qh1s[p2], qh2s[p2], flags[p2]
    mr3, mp3, mv3 = m_refid[p2], m_pos5[p2], m_rev[p2]
    pscore3, pidx3 = pscore[p2], pidx[p2]

    ekey_same = (
        (refid3 == _prev(refid3))
        & (pos53 == _prev(pos53))
        & (rev3 == _prev(rev3))
    )
    esame = (~ex3) & (~_prev(ex3)) & ekey_same
    esame = esame.at[0].set(False)
    eseg = jnp.cumsum(jnp.where(esame, 0, 1)) - 1

    # ---- pass 3: elections -----------------------------------------------
    any_pair = (
        zeros.at[eseg].max(mated3.astype(jnp.int32))[eseg] > 0
    )
    frag3 = (~ex3) & (~mated3)
    sel_f = elect(eseg, frag3, score3, (qh1_3, qh2_3, flag3, idx3))
    frag_dup = frag3 & (any_pair | ~sel_f)

    psame = (
        mated3
        & _prev(mated3)
        & ekey_same
        & (mr3 == _prev(mr3))
        & (mp3 == _prev(mp3))
        & (mv3 == _prev(mv3))
    )
    psame = psame.at[0].set(False)
    pseg = jnp.cumsum(jnp.where(psame, 0, 1)) - 1
    # Pair tie-break columns are all pair-level (the name hash is shared
    # by both mates), so the two row-side groups of a family elect
    # consistently.
    sel_p = elect(pseg, mated3, pscore3, (qh1_3, qh2_3, pidx3))
    pair_dup = mated3 & ~sel_p

    return jnp.zeros(n, bool).at[idx3].set(frag_dup | pair_dup)


def mark_duplicates_device(cols: Dict[str, np.ndarray]) -> np.ndarray:
    """bool[N] duplicate mask (original record order) from the job-global
    signature columns.  Rows are padded to the next power of two as
    exempt records so only O(log N) program shapes ever compile."""
    n = len(cols["refid"])
    if n == 0:
        return np.zeros(0, dtype=bool)
    padded = 1 << max(3, int(np.ceil(np.log2(n))))

    def pad(a, fill=0):
        out = np.full(padded, fill, dtype=np.int32)
        out[:n] = a
        return jnp.asarray(out)

    dup = _mark_core(
        pad(cols["refid"]),
        pad(cols["pos5"]),
        pad(cols["rev"]),
        pad(cols["exempt"], fill=1),  # padding never participates
        pad(cols["cand"]),
        pad(cols["score"]),
        pad(cols["qh1"]),
        pad(cols["qh2"]),
        pad(cols["flag"]),
    )
    return np.asarray(dup[:n])
