"""Device-resident duplicate marking, fused into the sorted stream.

Every production WGS pipeline runs duplicate marking straight after the
coordinate sort (biobambam's whole reason to exist; Sam2bam shows the win
of fusing such stages into one pass).  The sort already ships every
record's fixed fields to the chip, so marking duplicates there is nearly
free: this package adds the samtools-markdup-class decision as a fusion
stage over the sort's SoA columns, and the write path ORs
``FLAG_DUPLICATE`` (0x400) into the two flag bytes of each duplicate's
gathered record just before deflate — the LazyBAMRecord stance holds (the
sort never mutates the source payload bytes; only the per-part gather
output is patched).

Mask handoff to the writers: :func:`mark_duplicates_device` returns the
job-global bool mask in read order — the same index space the part
writers' ``order`` slices address.  On the host gather path the patch is
``io.bam.patch_flags`` over the gathered stream; on the device-resident
write path the per-part mask column rides up with the gather's offset
columns and the patch fuses into the on-chip gather itself
(``ops.pallas.gather_stream``: a compare against the flag-byte offsets,
no scatter) — both paths emit bit-identical parts.

Semantics (the single definition, shared bit-for-bit by the device path
and the pure-NumPy/Python oracle in :mod:`.oracle`):

- **Exempt** records are never marked and never participate: secondary
  (0x100), supplementary (0x800), unmapped (0x4 — or refid/pos < 0).
- Each participant's **end signature** is ``(refid, unclipped 5′, strand)``
  where the unclipped 5′ coordinate is ``ops.cigar.unclipped_start`` for
  forward reads and ``unclipped_end`` for reverse reads (clips restore the
  pre-trimming fragment boundary, so differently-clipped copies of one
  fragment collide).
- **Pair collation** groups candidates (paired, mate mapped) by a 64-bit
  murmur3 read-name hash; a name group of exactly two candidates is a
  mated pair, anything else demotes to fragments.  Mates exchange end
  signature and score along the collation order.
- **Pairs** sharing both end signatures form a duplicate family; the pair
  with the highest summed base quality (``ops.quality.sum_base_qualities``
  over both mates; ties → earliest record) survives, every other pair has
  both records marked.
- **Fragments** (unpaired, mate-unmapped, or demoted) sharing an end
  signature with any mated pair's end are all marked (pairs always beat
  fragments); otherwise the best-scoring fragment survives its family.

The decision itself runs on device (:mod:`.device`): three ``lax.sort``
passes over int32 signature columns plus segmented scatter reductions —
the same key-plumbing style as ``ops/keys.py``/``ops/sort.py``.  Ragged
inputs (clip spans, qual sums, name hashes) are gathered host-side per
split during the read, exactly like the unmapped-key ``hash32`` column
(:mod:`.signature`).
"""

from .device import mark_duplicates_device
from .oracle import mark_duplicates_oracle
from .signature import (
    DEDUP_EXTRA_FIELDS,
    concat_columns,
    signature_columns,
)

__all__ = [
    "DEDUP_EXTRA_FIELDS",
    "concat_columns",
    "mark_duplicates_device",
    "mark_duplicates_oracle",
    "signature_columns",
]
