"""Pure-host duplicate-marking oracle.

A deliberately independent implementation of the semantics documented in
:mod:`dedup` — per-record Python CIGAR walks, dict-based grouping, no
shared code with the vectorized signature columns or the device decision
— so the device path has a real oracle to be record-for-record identical
to, not a mirror of its own arithmetic.  Collation uses the actual read
name (the device uses a 64-bit murmur3 of it; the paths agree unless two
distinct names collide in 64 hash bits).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..ops.quality import MARKDUP_MIN_QUALITY
from ..spec import bam
from ..utils.murmur3 import murmurhash3_int32
from .signature import _QNAME_SEED2

_SCORE_CAP = 1 << 30

EndSig = Tuple[int, int, int]  # (refid, unclipped 5' pos, reverse bit)


def clip_walk(rec: bam.BamRecord) -> Tuple[int, int, int]:
    """(leading_clip, trailing_clip, ref_span) by a per-record walk."""
    ops = rec.cigar
    lead = 0
    for ln, op in ops:
        if op not in "SH":
            break
        lead += ln
    trail = 0
    for ln, op in reversed(ops):
        if op not in "SH":
            break
        trail += ln
    span = sum(ln for ln, op in ops if op in "MDN=X")
    return lead, trail, span


def unclipped_start(rec: bam.BamRecord) -> int:
    return rec.pos - clip_walk(rec)[0]


def unclipped_end(rec: bam.BamRecord) -> int:
    lead, trail, span = clip_walk(rec)
    return rec.pos + max(span, 1) - 1 + trail


def record_score(
    rec: bam.BamRecord, min_quality: int = MARKDUP_MIN_QUALITY
) -> int:
    """Summed base quality (Picard/samtools convention: bases ≥ 15 count;
    0xFF = missing qual never does)."""
    return min(
        sum(q for q in rec.qual if q >= min_quality and q != 0xFF),
        _SCORE_CAP,
    )


def end_signature(rec: bam.BamRecord) -> EndSig:
    rev = 1 if rec.flag & bam.FLAG_REVERSE else 0
    pos5 = unclipped_end(rec) if rev else unclipped_start(rec)
    return (rec.refid, pos5, rev)


def _exempt(rec: bam.BamRecord) -> bool:
    return bool(
        rec.flag
        & (bam.FLAG_SECONDARY | bam.FLAG_SUPPLEMENTARY | bam.FLAG_UNMAPPED)
    ) or rec.refid < 0 or rec.pos < 0


def _candidate(rec: bam.BamRecord) -> bool:
    return (
        not _exempt(rec)
        and bool(rec.flag & bam.FLAG_PAIRED)
        and not rec.flag & bam.FLAG_MATE_UNMAPPED
    )


def mark_duplicates_oracle(
    records: Sequence[bam.BamRecord],
) -> np.ndarray:
    """bool[N] duplicate mask over ``records`` (any order; the mask is
    positional)."""
    n = len(records)
    dup = np.zeros(n, dtype=bool)
    sig = [end_signature(r) for r in records]
    score = [record_score(r) for r in records]
    # Content tie-break columns (the election must be input-order-free):
    # the 64-bit name hash — the same words the device collation sorts by
    # — then the flag, then the index as the last resort.
    nh = [
        (
            murmurhash3_int32(r.raw[32 : 32 + r.l_read_name - 1], 0),
            murmurhash3_int32(
                r.raw[32 : 32 + r.l_read_name - 1], _QNAME_SEED2
            ),
        )
        for r in records
    ]

    # Pair collation by read name: exactly two candidates = a mated pair.
    templates: Dict[str, List[int]] = defaultdict(list)
    for i, r in enumerate(records):
        if _candidate(r):
            templates[r.read_name].append(i)
    pairs = [
        tuple(idxs) for idxs in templates.values() if len(idxs) == 2
    ]
    in_pair = {i for ij in pairs for i in ij}
    pair_end_sigs = {sig[i] for i in in_pair}

    # Pair families: unordered signature pair; best total score survives
    # (tie: the pair whose earliest record comes first).
    pair_fams: Dict[tuple, List[Tuple[int, int]]] = defaultdict(list)
    for i, j in pairs:
        pair_fams[tuple(sorted((sig[i], sig[j])))].append((i, j))
    for members in pair_fams.values():
        best = min(
            members,
            key=lambda ij: (
                -(score[ij[0]] + score[ij[1]]),
                nh[ij[0]],
                min(ij),
            ),
        )
        for ij in members:
            if ij != best:
                dup[ij[0]] = dup[ij[1]] = True

    # Fragment families: anything non-exempt outside a mated pair.  A
    # family sharing an end with any pair loses wholesale; otherwise the
    # best score survives (tie: earliest record).
    frag_fams: Dict[EndSig, List[int]] = defaultdict(list)
    for i, r in enumerate(records):
        if not _exempt(r) and i not in in_pair:
            frag_fams[sig[i]].append(i)
    for s, members in frag_fams.items():
        if s in pair_end_sigs:
            for i in members:
                dup[i] = True
            continue
        best = min(
            members,
            key=lambda i: (-score[i], nh[i], records[i].flag, i),
        )
        for i in members:
            if i != best:
                dup[i] = True
    return dup
