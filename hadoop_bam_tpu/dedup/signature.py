"""Host-side signature columns for duplicate marking.

One call per decoded split while the read loop is still holding the
batch's ragged sideband: everything ragged (CIGAR clip spans, qual sums,
read-name hashes) reduces to fixed-width int32 columns here, so the
global dedup decision downstream is pure device work over ~18 bytes per
record no matter how large the records are.  The same stance as the
unmapped-key ``hash32`` column in ``pipeline``: the host owns ragged
gathers, the chip owns the dense phases.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from ..collate.signature import QNAME_SEED2, name_hash_pair
from ..ops.cigar import clip_spans_np
from ..ops.quality import sum_base_qualities_np
from ..spec.bam import (
    FLAG_MATE_UNMAPPED,
    FLAG_PAIRED,
    FLAG_REVERSE,
    FLAG_SECONDARY,
    FLAG_SUPPLEMENTARY,
    FLAG_UNMAPPED,
)

#: SoA columns the dedup stage needs beyond ``io.bam.SORT_FIELDS``.
DEDUP_EXTRA_FIELDS = ("l_read_name", "n_cigar_op", "l_seq")

#: The collation engine owns the 64-bit read-name hash pair definition
#: (collate/signature.py); re-exported under the historical name.
_QNAME_SEED2 = QNAME_SEED2

#: Scores are clamped so a pair sum can never overflow int32 on device.
_SCORE_CAP = 1 << 30

_EXEMPT_FLAGS = FLAG_SECONDARY | FLAG_SUPPLEMENTARY | FLAG_UNMAPPED


def signature_columns(data: np.ndarray, soa: Dict) -> Dict[str, np.ndarray]:
    """Fixed-width dedup columns for one decoded batch (original order).

    Returns int32 arrays: ``refid``, ``pos5`` (orientation-aware unclipped
    5′ coordinate), ``rev``, ``exempt``, ``cand`` (pair-collation
    candidate), ``score``, ``qh1``/``qh2`` (64-bit read-name hash).
    """
    n = len(soa["rec_off"])
    refid = soa["refid"].astype(np.int32)
    pos = soa["pos"].astype(np.int64)
    flag = soa["flag"].astype(np.int32)
    rev = ((flag & FLAG_REVERSE) != 0).astype(np.int32)
    exempt = (
        ((flag & _EXEMPT_FLAGS) != 0) | (refid < 0) | (pos < 0)
    ).astype(np.int32)
    cand = (
        (exempt == 0)
        & ((flag & FLAG_PAIRED) != 0)
        & ((flag & FLAG_MATE_UNMAPPED) == 0)
    ).astype(np.int32)
    lead, trail, span = clip_spans_np(data, soa)
    pos5 = np.where(
        rev.astype(bool),
        pos + np.maximum(span, 1) - 1 + trail,  # unclipped_end
        pos - lead,  # unclipped_start
    ).astype(np.int32)
    score = np.minimum(
        sum_base_qualities_np(data, soa), _SCORE_CAP
    ).astype(np.int32)
    # The collation engine's 64-bit name hash pair (qname sans NUL).
    qh1, qh2 = name_hash_pair(data, soa)
    return {
        "refid": refid,
        "pos5": pos5,
        "rev": rev,
        "exempt": exempt,
        "cand": cand,
        "score": score,
        "qh1": qh1,
        "qh2": qh2,
        "flag": flag,  # content tie-break column for the election
    }


def concat_columns(
    parts: Sequence[Dict[str, np.ndarray]]
) -> Dict[str, np.ndarray]:
    """Concatenate per-split column dicts into the job-global columns."""
    if not parts:
        return {
            k: np.empty(0, np.int32)
            for k in (
                "refid", "pos5", "rev", "exempt", "cand", "score",
                "qh1", "qh2", "flag",
            )
        }
    if len(parts) == 1:
        return parts[0]
    return {
        k: np.concatenate([p[k] for p in parts]) for k in parts[0]
    }
