"""Post-job merge: headerless parts → one valid BAM (+merged splitting-bai).

Reference util/SAMFileMerger.java:46-148 semantics: require the `_SUCCESS`
marker, glob ``part-[mr]-*`` in order, write the header block
(SAMOutputPreparer equivalent), concatenate the part bytes untouched (they
carry no header and no terminator), append the BGZF terminator, and merge the
per-part `.splitting-bai`s by shifting each part's virtual offsets by the
byte length of everything before it (:104-148).
"""

from __future__ import annotations

import os
from typing import List, Optional

from ..spec import bam, bgzf, cram, indices
from ..utils import nio


def prepare_bam_header_block(header: bam.BamHeader, level: int = 6) -> bytes:
    """The leading BGZF stream holding magic+header+refs
    (util/SAMOutputPreparer.java:95-127)."""
    import io as _io

    buf = _io.BytesIO()
    w = bgzf.BgzfWriter(buf, level=level, append_terminator=False)
    w.write(header.encode())
    w.close()
    return buf.getvalue()


def merge_bam_parts(
    part_dir: str,
    out_path: str,
    header: bam.BamHeader,
    write_splitting_bai: bool = False,
    check_success: bool = True,
) -> None:
    if check_success:
        nio.check_success(part_dir)
    parts = nio.list_parts(part_dir)
    header_block = prepare_bam_header_block(header)
    part_lengths: List[int] = []
    with open(out_path, "wb") as out:
        out.write(header_block)
        for p in parts:
            with open(p, "rb") as f:
                data = f.read()
            out.write(data)
            part_lengths.append(len(data))
        out.write(bgzf.TERMINATOR)
    total = os.path.getsize(out_path)

    if write_splitting_bai:
        part_indices: List[indices.SplittingBai] = []
        ok = True
        for p in parts:
            ip = str(p) + indices.SPLITTING_BAI_EXT
            if not os.path.exists(ip):
                ok = False
                break
            part_indices.append(indices.SplittingBai.load(ip))
        if ok and part_indices:
            with open(out_path + indices.SPLITTING_BAI_EXT, "wb") as f:
                indices.merge_splitting_bais(
                    part_indices,
                    part_lengths,
                    header_length=len(header_block),
                    total_length=total,
                    out=f,
                )


def merge_cram_parts(
    part_dir: str,
    out_path: str,
    header: bam.BamHeader,
    check_success: bool = True,
) -> None:
    """Headerless CRAM parts → one valid CRAM: file definition + header
    container, part containers untouched, EOF marker appended
    (util/SAMFileMerger.java:77-78,96-102 CRAM arm)."""
    if check_success:
        nio.check_success(part_dir)
    parts = nio.list_parts(part_dir)
    with open(out_path, "wb") as out:
        out.write(cram.MAGIC + bytes([3, 0]) + b"\x00" * 20)
        out.write(cram.encode_file_header_container(header.text, 3))
        nio.concat_files(parts, out)
        out.write(cram.EOF_V3)
