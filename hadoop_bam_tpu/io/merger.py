"""Post-job merge: headerless parts → one valid BAM (+merged splitting-bai).

Reference util/SAMFileMerger.java:46-148 semantics: require the `_SUCCESS`
marker, glob ``part-[mr]-*`` in order, write the header block
(SAMOutputPreparer equivalent), concatenate the part bytes untouched (they
carry no header and no terminator), append the BGZF terminator, and merge the
per-part `.splitting-bai`s by shifting each part's virtual offsets by the
byte length of everything before it (:104-148).
"""

from __future__ import annotations

import io
import os
from typing import List, Optional

from ..spec import bam, bgzf, cram, indices
from ..utils import nio


def prepare_bam_header_block(header: bam.BamHeader, level: int = 6) -> bytes:
    """The leading BGZF stream holding magic+header+refs
    (util/SAMOutputPreparer.java:95-127)."""
    import io as _io

    buf = _io.BytesIO()
    w = bgzf.BgzfWriter(buf, level=level, append_terminator=False)
    w.write(header.encode())
    w.close()
    return buf.getvalue()


def _append_file(out, path: str) -> int:
    """Append ``path``'s bytes to the open binary stream ``out``; returns
    the byte count.  Uses ``os.sendfile`` (kernel-side copy, no userspace
    round trip) when the destination is a real file, falling back to a
    buffered copy."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        sent = 0
        try:
            out.flush()
            while sent < size:
                n = os.sendfile(out.fileno(), f.fileno(), sent, size - sent)
                if n == 0:
                    break
                sent += n
            if sent == size:
                out.seek(0, os.SEEK_END)
                return size
        except (AttributeError, OSError, io.UnsupportedOperation):
            pass
        # Fallback resumes exactly where sendfile stopped — including when
        # it stopped by raising mid-copy (the kernel fd offset already
        # advanced by ``sent``; re-sync the buffered stream to it).
        out.seek(0, os.SEEK_END)
        f.seek(sent)
        import shutil

        shutil.copyfileobj(f, out, 4 << 20)
    return size


def merge_bam_parts(
    part_dir: str,
    out_path: str,
    header: bam.BamHeader,
    write_splitting_bai: bool = False,
    check_success: bool = True,
) -> None:
    if check_success:
        nio.check_success(part_dir)
    parts = nio.list_parts(part_dir)
    header_block = prepare_bam_header_block(header)
    part_lengths: List[int] = []
    with open(out_path, "wb") as out:
        out.write(header_block)
        for p in parts:
            part_lengths.append(_append_file(out, p))
        out.write(bgzf.TERMINATOR)
    total = os.path.getsize(out_path)

    if write_splitting_bai:
        part_indices: List[indices.SplittingBai] = []
        ok = True
        for p in parts:
            ip = str(p) + indices.SPLITTING_BAI_EXT
            if not os.path.exists(ip):
                ok = False
                break
            part_indices.append(indices.SplittingBai.load(ip))
        if ok and part_indices:
            with open(out_path + indices.SPLITTING_BAI_EXT, "wb") as f:
                indices.merge_splitting_bais(
                    part_indices,
                    part_lengths,
                    header_length=len(header_block),
                    total_length=total,
                    out=f,
                )


def merge_cram_parts(
    part_dir: str,
    out_path: str,
    header: bam.BamHeader,
    check_success: bool = True,
) -> None:
    """Headerless CRAM parts → one valid CRAM: file definition + header
    container, part containers untouched, EOF marker appended
    (util/SAMFileMerger.java:77-78,96-102 CRAM arm)."""
    if check_success:
        nio.check_success(part_dir)
    parts = nio.list_parts(part_dir)
    with open(out_path, "wb") as out:
        out.write(cram.MAGIC + bytes([3, 0]) + b"\x00" * 20)
        out.write(cram.encode_file_header_container(header.text, 3))
        nio.concat_files(parts, out)
        out.write(cram.EOF_V3)
