"""BCF input/output: record-aligned split planning, batched reading, writer.

Reference parity:
- ``BcfSplitGuesser``: find a record start inside an arbitrary byte range,
  handling both BGZF and uncompressed BCF, with the reference's candidate
  sanity rules — plausible l_shared/l_indiv, CHROM within the contig
  dictionary, POS/rlen sane, n_sample == header sample count, ID field is a
  typed string — then verification by decoding 2 whole BGZF blocks
  (compressed) or a 0x80000-byte window (uncompressed)
  (BCFSplitGuesser.java:61-75,118-360),
- ``BcfInputFormat``: byte splits fixed up to record starts
  (VCFInputFormat.fixBCFSplits/addGuessedSplits, VCFInputFormat.java:302-385),
- ``BcfRecordWriter``: always-BGZF output with swallowed-header part mode
  (BCFRecordWriter.java:49-178).
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Tuple

import numpy as np

from ..conf import (
    Configuration,
    ERRORS_MODE,
    VCF_INTERVALS,
    VCFRECORDREADER_VALIDATION_STRINGENCY,
)
from ..spec import bcf, bgzf
from ..spec.vcf import VcfHeader, variant_key
from ..utils.intervals import Interval, parse_intervals
from ..utils.tracing import METRICS
from . import fs
from .splits import FileVirtualSplit
from .vcf import VariantBatch

# Verification bounds (BCFSplitGuesser.java:61-75).
BGZF_BLOCKS_NEEDED_FOR_GUESS = 2
UNCOMPRESSED_BYTES_NEEDED_FOR_GUESS = 0x80000


class BcfSplitGuesser:
    """Find the first real BCF record start in ``[beg, end)``."""

    def __init__(self, data: bytes, header: bcf.BcfHeader, compressed: Optional[bool] = None):
        self.data = data
        self.header = header
        self.compressed = (
            bgzf.is_bgzf(data) if compressed is None else compressed
        )

    # -- candidate scan (vectorized over every payload offset) --------------

    def _candidate_offsets(self, payload: np.ndarray) -> np.ndarray:
        """Offsets passing the sanity rules (BCFSplitGuesser.java:273-360)."""
        n = len(payload)
        # minimal record: 8-byte lengths + 24-byte fixed shared fields
        if n < 33:
            return np.empty(0, dtype=np.int64)
        count = n - 32
        pad = np.zeros(40, dtype=np.uint8)
        a = np.concatenate([payload, pad])

        def u32(off: int) -> np.ndarray:
            return (
                a[off : off + count].astype(np.uint64)
                | (a[off + 1 : off + count + 1].astype(np.uint64) << 8)
                | (a[off + 2 : off + count + 2].astype(np.uint64) << 16)
                | (a[off + 3 : off + count + 3].astype(np.uint64) << 24)
            )

        l_shared = u32(0)
        l_indiv = u32(4)
        chrom = u32(8).astype(np.int64).astype(np.int32)
        pos = u32(12).astype(np.int64).astype(np.int32)
        rlen = u32(16).astype(np.int64).astype(np.int32)
        nai = u32(24)
        n_allele = (nai >> np.uint64(16)).astype(np.int64)
        nfs = u32(28)
        n_sample = (nfs & np.uint64(0xFFFFFF)).astype(np.int64)

        ok = (l_shared >= 24) & (l_shared < 1 << 24) & (l_indiv < 1 << 28)
        ok &= (chrom >= 0) & (chrom < len(self.header.contigs))
        ok &= (pos >= -1) & (rlen >= 0)
        ok &= n_allele < 0xFFFF
        ok &= n_sample == self.header.n_samples
        # ID field begins right after the fixed 24 shared bytes: its typed
        # descriptor must be a string (char) or missing (:340-352).
        id_desc = a[32 : 32 + count]
        ok &= ((id_desc & 0xF) == bcf.T_CHAR) | (id_desc == 0)
        return np.nonzero(ok)[0].astype(np.int64)

    # -- verification --------------------------------------------------------

    def _decodes_from(self, payload: bytes, p: int, need_bytes: int) -> bool:
        """True iff consecutive records decode from ``p`` until the window is
        exhausted (truncation mid-record after ≥1 success is acceptable)."""
        decoded = 0
        limit = min(len(payload), p + need_bytes)
        while p + 8 <= limit:
            l_shared, l_indiv = struct.unpack_from("<II", payload, p)
            if p + 8 + l_shared + l_indiv > len(payload):
                # Starts in the window but extends past the buffer: truncation
                # is acceptable iff ≥1 record already decoded (:248-263).
                return decoded > 0
            try:
                _, p = bcf.decode_record(payload, p, self.header)
            except (bcf.BcfError, struct.error, IndexError, ValueError, KeyError):
                return False
            decoded += 1
        return decoded > 0

    def guess_next_record_start(self, beg: int, end: int) -> Optional[int]:
        """Virtual offset of the first verifiable record in the byte range
        ``[beg, end)``; None when none found.  Uncompressed files use the
        degenerate ``offset<<16`` voffset form so both kinds flow through the
        same FileVirtualSplit machinery.  Guess cost is visible in
        ``--metrics`` via the ``bcf.guess.*`` counters (windows scanned,
        candidates sanity-passed, verified hits)."""
        METRICS.count("bcf.guess.windows", 1)
        g = (
            self._guess_bgzf(beg, end)
            if self.compressed
            else self._guess_plain(beg, end)
        )
        if g is not None:
            METRICS.count("bcf.guess.verified", 1)
        return g

    def _guess_plain(self, beg: int, end: int) -> Optional[int]:
        window = self.data[
            beg : min(len(self.data), end + UNCOMPRESSED_BYTES_NEEDED_FOR_GUESS)
        ]
        arr = np.frombuffer(window, dtype=np.uint8)
        in_range = self._candidate_offsets(arr)
        METRICS.count("bcf.guess.candidates", len(in_range))
        for off in in_range:
            if off >= end - beg:
                break
            if self._decodes_from(
                window, int(off), UNCOMPRESSED_BYTES_NEEDED_FOR_GUESS
            ):
                return (beg + int(off)) << 16
        return None

    def _guess_bgzf(self, beg: int, end: int) -> Optional[int]:
        from .. import native

        pos = beg
        while True:
            cp = native.find_next_block(self.data, pos, min(end, len(self.data)))
            if cp < 0 or cp >= end:
                return None
            # Inflate this block + enough successors for verification.
            co, cs_l, us_l = [], [], []
            p = cp
            while len(co) < BGZF_BLOCKS_NEEDED_FOR_GUESS + 2 and p < len(self.data):
                try:
                    csize, usize = bgzf.read_block_at(self.data, p)
                except bgzf.BgzfError:
                    break
                co.append(p)
                cs_l.append(csize)
                us_l.append(usize)
                p += csize
            if co:
                try:
                    out, offs = native.inflate_blocks(
                        self.data,
                        np.asarray(co, dtype=np.int64),
                        np.asarray(cs_l, dtype=np.int32),
                        np.asarray(us_l, dtype=np.int32),
                    )
                    payload = out.tobytes()
                    first_len = int(offs[1] - offs[0]) if len(offs) > 1 else len(payload)
                    cands = self._candidate_offsets(
                        np.frombuffer(payload[:first_len], dtype=np.uint8)
                    )
                    METRICS.count("bcf.guess.candidates", len(cands))
                    for up in cands:
                        if self._decodes_from(
                            payload,
                            int(up),
                            sum(us_l[:BGZF_BLOCKS_NEEDED_FOR_GUESS]),
                        ):
                            return (cp << 16) | int(up)
                except bgzf.BgzfError:
                    pass
            pos = cp + 1


def read_bcf_header(
    data: bytes, compressed: Optional[bool] = None
) -> Tuple[bcf.BcfHeader, int]:
    """(header, offset of first record in the *uncompressed* stream),
    inflating only as many leading blocks as the header occupies."""
    if compressed is None:
        compressed = bgzf.is_bgzf(data)
    if not compressed:
        return bcf.decode_header(data)
    chunk = bytearray()
    pos = 0
    while pos < len(data):
        payload, csize = bgzf.inflate_block(data, pos)
        chunk.extend(payload)
        pos += csize
        if len(chunk) >= 9:
            (l_text,) = struct.unpack_from("<I", chunk, 5)
            if len(chunk) >= 9 + l_text:
                break
    return bcf.decode_header(bytes(chunk))


class BcfInputFormat:
    """BCF split planning + batched reading (VCFInputFormat BCF arm)."""

    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()

    def _stringency(self) -> str:
        s = (
            self.conf.get(VCFRECORDREADER_VALIDATION_STRINGENCY, "STRICT")
            or "STRICT"
        ).upper()
        return s

    def _intervals(self) -> Optional[List[Interval]]:
        return parse_intervals(self.conf.get(VCF_INTERVALS))

    def get_splits(
        self, paths, split_size: int = 4 << 20
    ) -> List[FileVirtualSplit]:
        """Byte ranges fixed up to record starts with the guesser
        (VCFInputFormat.java:302-385).  Virtual offsets for BGZF files, plain
        ``offset<<16`` voffsets for uncompressed ones so one split type serves
        both (the reference uses FileVirtualSplit the same way)."""
        out: List[FileVirtualSplit] = []
        for path in sorted(paths):
            # Planning reads the file once through the seam (the guesser
            # needs verify windows across it — the client-side cost the
            # reference's BCFSplitGuesser pays too).
            data = fs.get_fs(path).read_all(path)
            compressed = bgzf.is_bgzf(data)
            hdr, first = read_bcf_header(data, compressed)
            guesser = BcfSplitGuesser(data, hdr, compressed)
            size = len(data)
            bounds = list(range(0, size, split_size)) + [size]
            starts: List[int] = []
            for beg in bounds[:-1]:
                g = guesser.guess_next_record_start(beg, min(beg + split_size, size))
                if g is not None:
                    starts.append(g)
            # First record of the file is authoritative for split 0.
            if compressed:
                acc = 0
                v0 = 0
                for b in bgzf.scan_blocks(data):
                    if first < acc + b.usize:
                        v0 = bgzf.make_voffset(b.coffset, first - acc)
                        break
                    acc += b.usize
            else:
                v0 = first << 16
            starts = sorted(set([v0] + [s for s in starts if s > v0]))
            vend = (size << 16) | 0xFFFF if compressed else size << 16
            for i, s in enumerate(starts):
                e = starts[i + 1] if i + 1 < len(starts) else vend
                if e > s:
                    out.append(FileVirtualSplit(path, s, e))
        return out

    def read_split(
        self,
        split: FileVirtualSplit,
        data: Optional[bytes] = None,
        stream=None,
        inflate_fn=None,
        errors: Optional[str] = None,
    ) -> VariantBatch:
        """Decode one split.  ``stream`` (a ``DeviceStream``) arms the
        device record-chain walk; ``inflate_fn`` routes the window's BGZF
        member inflate through a caller-supplied batch codec (the serve
        ``LaneBatcher``); ``errors`` (default ``hadoopbam.errors`` conf,
        ``"strict"``) selects member-corruption policy — strict raises
        through the CRC gate, salvage quarantines exactly the bad member
        and re-syncs the record chain with the guesser, survivors decoded
        by the exact ``spec/bcf.py`` oracle."""
        stringency = self._stringency()
        intervals = self._intervals()
        if errors is None:
            errors = self.conf.get(ERRORS_MODE, "strict") or "strict"
        if data is None:
            # Split-local: the header comes from a growing prefix read and
            # the record range from its own byte window — a split costs
            # O(header + split), not O(file).  Split ends are record-start
            # voffsets (the planner's contract), so no record spills past
            # the window's end-block margin.
            hdr, payload, p, end, breaks = _read_bcf_split_local(
                split, errors=errors, inflate_fn=inflate_fn
            )
        else:
            compressed = bgzf.is_bgzf(data)
            if compressed:
                payload, p, end, breaks = _inflate_range(
                    data,
                    split.vstart,
                    split.vend,
                    errors=errors,
                    inflate_fn=inflate_fn,
                )
            else:
                payload = data
                p = split.vstart >> 16
                end = split.vend >> 16
                breaks = []
            hdr, _ = read_bcf_header(data, compressed)
        if breaks:
            # A quarantined member tore the record chain: the salvage walk
            # re-syncs with the guesser and decodes survivors with the
            # exact oracle (no device/vectorized shortcut on a torn chain).
            return _salvage_walk(payload, p, end, breaks, hdr, intervals)
        if stream is not None:
            dev = _read_device(payload, p, end, hdr, intervals, stream)
            if dev is not None:
                return dev
        fast = _read_vectorized(payload, p, end, hdr, intervals)
        if fast is not None:
            return fast
        variants: List[bcf.BcfVariant] = []
        while p + 8 <= end:
            try:
                v, p = bcf.decode_record(payload, p, hdr)
            except (bcf.BcfError, struct.error, IndexError, ValueError, KeyError):
                if stringency == "STRICT":
                    raise
                break
            if intervals is not None and not any(
                iv.overlaps(v.chrom, v.start, v.end) for iv in intervals
            ):
                continue
            variants.append(v)
        keys = np.array(
            [variant_key(hdr.vcf, v) for v in variants], dtype=np.int64
        )
        pos = np.array([v.pos for v in variants], dtype=np.int64)
        endp = np.array([v.end for v in variants], dtype=np.int64)
        return VariantBatch(
            header=hdr.vcf, variants=variants, keys=keys, pos=pos, end=endp
        )


def _read_vectorized(
    payload, p: int, end: int, hdr: bcf.BcfHeader, intervals
) -> Optional[VariantBatch]:
    """Batched BCF split decode (VERDICT r3 #4): one serial chain walk
    finds every record boundary, the fixed-width shared prefix decodes as
    NumPy gathers over the whole payload, and the 64-bit keys and
    pos/end columns come out as array ops — no per-record Python.  The
    ``VariantContext`` rows stay lazy (``bcf.decode_record`` runs only if
    a consumer asks — the LazyBCFGenotypesContext stance one level up).

    Returns None when anything needs the exact per-record path — a
    truncated/misaligned chain, a CHROM outside the dictionaries, any
    typed value the C validator cannot prove the exact decoder would
    accept (bad type codes, out-of-range dictionary indexes, shared-block
    length mismatches, ambiguous INFO END) — so the exact parser's error
    semantics (incl. STRICT stringency raises) stay the contract."""
    from .. import native

    a = (
        payload
        if isinstance(payload, np.ndarray)
        else np.frombuffer(payload, np.uint8)
    )
    if not native.available():
        return None  # the chain walk is serial: C or nothing
    try:
        end_key = hdr.strings.index("END") if "END" in hdr.strings else -1
        offs, ref_len, end_info = native.bcf_scan(
            a, p, end, len(hdr.contigs), len(hdr.strings), end_key
        )
    except ValueError:
        return None
    n = len(offs)
    if n == 0:
        return VariantBatch(header=hdr.vcf, variants=[])

    def i32(at: np.ndarray) -> np.ndarray:
        return (
            a[at].astype(np.uint32)
            | (a[at + 1].astype(np.uint32) << 8)
            | (a[at + 2].astype(np.uint32) << 16)
            | (a[at + 3].astype(np.uint32) << 24)
        ).astype(np.int32)

    body = offs + 8
    chrom_i = i32(body)
    pos0 = i32(body + 4).astype(np.int64)
    # BCF contig order need not match the VCF header's contig-line order
    # (IDX= overrides): map through the VCF dictionary once per contig
    # (contig_index never raises — unknown names get the murmur3 key).
    vmap = np.empty(len(hdr.contigs), dtype=np.int64)
    for ci, name in enumerate(hdr.contigs):
        vmap[ci] = hdr.vcf.contig_index(name)
    idx = vmap[chrom_i]
    # variant_key semantics including the Java sign-extension quirk: a
    # negative (pos-1) floods the high word (POS=0 telomeric records).
    keys = (idx << 32) | np.where(pos0 < 0, pos0, pos0 & 0xFFFFFFFF)
    pos1 = pos0 + 1
    # end: INFO END when present (the exact path's END= regex), else
    # pos + len(REF) - 1 — both extracted by the C scan.
    endp = np.where(
        end_info != np.iinfo(np.int64).min, end_info, pos0 + ref_len
    )

    if intervals is not None:
        name_to_ci = {name: ci for ci, name in enumerate(hdr.contigs)}
        keep = np.zeros(n, dtype=bool)
        for iv in intervals:
            ci = name_to_ci.get(iv.contig)
            if ci is None:
                continue
            keep |= (
                (chrom_i == ci) & (pos1 <= iv.end) & (endp >= iv.start)
            )
        offs, keys, pos1, endp = (
            offs[keep], keys[keep], pos1[keep], endp[keep]
        )

    kept = offs

    def materialize() -> List[bcf.BcfVariant]:
        out: List[bcf.BcfVariant] = []
        for o in kept:
            v, _ = bcf.decode_record(payload, int(o), hdr)
            out.append(v)
        return out

    return VariantBatch(
        header=hdr.vcf,
        keys=keys,
        pos=pos1,
        end=endp,
        materializer=materialize,
    )


def _read_bcf_header_prefix(path: str):
    """(header, compressed?) via growing prefix reads — O(header) bytes."""
    f = fs.get_fs(path)
    size = f.size(path)
    n = 8 << 10
    while True:
        prefix = f.read_range(path, 0, min(n, size))
        compressed = bgzf.is_bgzf(prefix)
        try:
            hdr, _ = read_bcf_header(prefix, compressed)
            return hdr, compressed
        except (bcf.BcfError, bgzf.BgzfError, struct.error, IndexError):
            if n >= size:
                raise
            n *= 4


def _read_device(payload, p: int, end: int, hdr: bcf.BcfHeader, intervals, stream):
    """The armed variant-plane read: device record-chain walk + the ragged
    interval join, columns bit-exact with ``_read_vectorized`` (same key /
    pos / end math; ``end`` comes from ``rlen``, which our encoder writes
    from ``VariantContext.end`` — INFO END included — so the columns agree
    on round-tripped corpora; a foreign writer disagreeing on rlen is the
    documented residue).  Returns None to fall through to the host tiers —
    per *window*, never a sticky disable."""
    res = stream.walk_bcf_records(payload, p, end)
    if res is None:
        return None
    cols, n, ok, tier = res
    if tier == "device":
        METRICS.count("bcf.chain.device_walks", 1)
    else:
        METRICS.count("bcf.chain.host_walks", 1)
        METRICS.count("bcf.chain.tierdowns", 1)
    if not ok:
        # Corrupt/truncated framing: the exact decoder owns the error
        # semantics (STRICT raises and all) — fall through.
        METRICS.count("bcf.chain.oracle_fallbacks", 1)
        return None
    METRICS.count("bcf.chain.records", int(n))
    offs, chrom_i, pos0, rlen = cols[0], cols[1], cols[2], cols[3]
    if n and (
        int(chrom_i.min()) < 0 or int(chrom_i.max()) >= len(hdr.contigs)
    ):
        return None  # CHROM outside the dictionary: exact path's error
    pos0 = pos0.astype(np.int64)
    vmap = np.empty(max(len(hdr.contigs), 1), dtype=np.int64)
    for ci, name in enumerate(hdr.contigs):
        vmap[ci] = hdr.vcf.contig_index(name)
    idx = vmap[chrom_i] if n else np.empty(0, np.int64)
    keys = (idx << 32) | np.where(pos0 < 0, pos0, pos0 & 0xFFFFFFFF)
    pos1 = pos0 + 1
    endp = pos0 + rlen.astype(np.int64)

    kept = np.asarray(offs, np.int64)
    if intervals is not None:
        from ..ops.pallas.overlap import ragged_overlap_mask

        name_to_ci = {name: ci for ci, name in enumerate(hdr.contigs)}
        q = [
            (name_to_ci[iv.contig], iv.start - 1, iv.end)
            for iv in intervals
            if iv.contig in name_to_ci
        ]
        q_rid = np.asarray([r for r, _, _ in q], np.int64)
        q_beg = np.asarray([b for _, b, _ in q], np.int64)
        q_end = np.asarray([e for _, _, e in q], np.int64)
        # The join's device form rides int32 lanes; a coordinate outside
        # that domain tiers this window's join down to the NumPy twin.
        use_dev = bool(
            n == 0
            or (int(endp.max()) < 2**31 and int(q_end.max(initial=0)) < 2**31)
        )
        METRICS.count(
            "variants.join_device" if use_dev else "variants.join_host", 1
        )
        keep = ragged_overlap_mask(
            chrom_i, pos0, endp, q_rid, q_beg, q_end, use_device=use_dev
        )
        kept, keys, pos1, endp = (
            kept[keep], keys[keep], pos1[keep], endp[keep]
        )

    def materialize() -> List[bcf.BcfVariant]:
        out: List[bcf.BcfVariant] = []
        for o in kept:
            v, _ = bcf.decode_record(payload, int(o), hdr)
            out.append(v)
        return out

    return VariantBatch(
        header=hdr.vcf,
        keys=keys,
        pos=pos1,
        end=endp,
        materializer=materialize,
    )


def _find_resync(payload, start: int, hdr: bcf.BcfHeader) -> Optional[int]:
    """First verifiable record start at/after ``start`` — the guesser's
    candidate+verify pass applied to an already-inflated stream (the
    salvage re-sync after a quarantined member)."""
    g = BcfSplitGuesser(b"", hdr, compressed=False)
    window = payload[start : start + UNCOMPRESSED_BYTES_NEEDED_FOR_GUESS]
    cands = g._candidate_offsets(np.frombuffer(window, dtype=np.uint8))
    METRICS.count("bcf.guess.candidates", len(cands))
    for off in cands:
        if g._decodes_from(
            payload, start + int(off), UNCOMPRESSED_BYTES_NEEDED_FOR_GUESS
        ):
            return start + int(off)
    return None


def _salvage_walk(
    payload, p: int, end: int, breaks: List[int], hdr: bcf.BcfHeader, intervals
) -> VariantBatch:
    """Exact-decoder walk over a chain torn by quarantined members.

    ``breaks`` are payload offsets where inflated bytes are missing: a
    record extending across one is torn (dropped, counted
    ``salvage.records_dropped``); the chain re-syncs at the next
    guesser-verified record start, so every survivor decodes through the
    same ``spec/bcf.py`` oracle as a clean read — oracle-exact."""
    variants: List[bcf.BcfVariant] = []
    bq = sorted(b for b in breaks if b is not None)
    bi = 0
    while bq and bi < len(bq) and bq[bi] <= p:
        # Chain torn at/before the split start: re-sync immediately.
        r = _find_resync(payload, bq[bi], hdr)
        bi += 1
        if r is None:
            break
        p = r
    while p + 8 <= end:
        b = bq[bi] if bi < len(bq) else None
        if b is not None and p >= b:
            bi += 1
            r = _find_resync(payload, b, hdr)
            if r is None:
                break
            p = r
            continue
        torn = False
        if b is not None:
            l_shared, l_indiv = struct.unpack_from("<II", payload, p)
            torn = p + 8 + l_shared + l_indiv > b
        if torn:
            # The rest of this record was quarantined with its member.
            METRICS.count("salvage.records_dropped", 1)
            bi += 1
            r = _find_resync(payload, b, hdr)
            if r is None:
                break
            p = r
            continue
        try:
            v, p = bcf.decode_record(payload, p, hdr)
        except (bcf.BcfError, struct.error, IndexError, ValueError, KeyError):
            METRICS.count("salvage.records_dropped", 1)
            break
        if intervals is not None and not any(
            iv.overlaps(v.chrom, v.start, v.end) for iv in intervals
        ):
            continue
        variants.append(v)
    keys = np.array(
        [variant_key(hdr.vcf, v) for v in variants], dtype=np.int64
    )
    pos = np.array([v.pos for v in variants], dtype=np.int64)
    endp = np.array([v.end for v in variants], dtype=np.int64)
    return VariantBatch(
        header=hdr.vcf, variants=variants, keys=keys, pos=pos, end=endp
    )


def _read_bcf_split_local(
    split: FileVirtualSplit, errors: str = "strict", inflate_fn=None
):
    """(header, payload, start, record-start limit, chain breaks) reading
    only the split's byte window + a growing header prefix."""
    hdr, compressed = _read_bcf_header_prefix(split.path)
    f = fs.get_fs(split.path)
    if compressed:
        c0 = split.vstart >> 16
        c1 = split.vend >> 16
        # The end block's full extent (≤64KiB) plus slack.
        window = f.read_range(split.path, c0, (c1 - c0) + 0x20000)
        shift = c0 << 16
        payload, p, end, breaks = _inflate_range(
            window,
            split.vstart - shift,
            split.vend - shift,
            errors=errors,
            inflate_fn=inflate_fn,
        )
        return hdr, payload, p, end, breaks
    p = split.vstart >> 16
    end = split.vend >> 16
    window = f.read_range(split.path, p, end - p)
    return hdr, window, 0, end - p, []


def _inflate_range(
    data: bytes,
    vstart: int,
    vend: int,
    errors: str = "strict",
    inflate_fn=None,
) -> Tuple[bytes, int, int, List[int]]:
    """Inflate the BGZF blocks covering [vstart, vend) → (payload, start
    offset, record-start limit, chain-break offsets).  Records *start*
    strictly before the limit; the tail block at vend's coffset is included
    so a record straddling the boundary completes (the BGZFLimitingStream
    role, BCFRecordReader.java:176-236).

    Member corruption policy: ``errors="strict"`` raises the ``BgzfError``
    through the CRC gate; ``"salvage"`` quarantines exactly the bad member
    (``salvage.members_quarantined``/``salvage.bytes_quarantined``) and
    records a chain break at the payload offset where its bytes are
    missing — the record walk re-syncs there.

    ``inflate_fn(data, coffsets, csizes, usizes) -> (out, offsets)``
    (the ``DeviceStream.decode_members`` contract, e.g. the serve
    ``LaneBatcher``) inflates the scanned member table as one coalesced
    batch; any batch failure falls back to the per-member host loop so
    the error policy above stays exact."""
    c0, u0 = bgzf.split_voffset(vstart)
    c1, u1 = bgzf.split_voffset(vend)
    # Pass 1: scan the member table (headers only).
    members: List[Tuple[int, int, int]] = []  # (coffset, csize, usize)
    bad_headers: List[int] = []  # index into the member order of breaks
    pos = c0
    end_block_index = None
    while pos < len(data) and pos <= c1:
        if pos == c1:
            end_block_index = len(members)
        try:
            csize, usize = bgzf.read_block_at(data, pos)
        except bgzf.BgzfError:
            if errors != "salvage":
                raise
            # Header unreadable: quarantine up to the next plausible
            # member magic and mark a chain break here.
            from .. import native

            nxt = native.find_next_block(data, pos + 1, min(len(data), c1 + 1))
            if nxt < 0:
                nxt = len(data)
            METRICS.count("salvage.members_quarantined", 1)
            METRICS.count("salvage.bytes_quarantined", nxt - pos)
            bad_headers.append(len(members))
            pos = nxt
            continue
        members.append((pos, csize, usize))
        pos += csize
    chunks: List[Optional[bytes]] = [None] * len(members)
    if inflate_fn is not None and members:
        try:
            out, offs = inflate_fn(
                np.frombuffer(data, np.uint8),
                np.asarray([m[0] for m in members], np.int64),
                np.asarray([m[1] for m in members], np.int32),
                np.asarray([m[2] for m in members], np.int32),
            )
            raw = out.tobytes()
            for i in range(len(members)):
                a = int(offs[i])
                b = int(offs[i + 1]) if i + 1 < len(offs) else len(raw)
                chunks[i] = raw[a:b]
        except Exception:
            chunks = [None] * len(members)  # per-member host loop below
    for i, (mpos, csize, usize) in enumerate(members):
        if chunks[i] is not None:
            continue
        try:
            payload, _ = bgzf.inflate_block(data, mpos)
            chunks[i] = payload
        except bgzf.BgzfError:
            if errors != "salvage":
                raise
            METRICS.count("salvage.members_quarantined", 1)
            METRICS.count("salvage.bytes_quarantined", csize)
            chunks[i] = b""
            bad_headers.append(i)
    # Pass 2: concatenate and translate member-order breaks to payload
    # offsets (a break lands where the quarantined bytes would have been).
    blob_parts: List[bytes] = []
    acc = 0
    acc_before_end_block = None
    break_at: List[int] = []
    bad = sorted(set(bad_headers))
    bj = 0
    for i in range(len(members) + 1):
        while bj < len(bad) and bad[bj] == i:
            break_at.append(acc)
            bj += 1
        if i == end_block_index:
            acc_before_end_block = acc
        if i < len(members) and chunks[i]:
            blob_parts.append(chunks[i])
            acc += len(chunks[i])
    blob = b"".join(blob_parts)
    limit = (
        len(blob)
        if acc_before_end_block is None
        else min(acc_before_end_block + u1, len(blob))
    )
    return blob, u0, limit, sorted(set(break_at))


class BcfRecordWriter:
    """Always-BGZF BCF writer with headerless part mode
    (BCFRecordWriter.java:49-138)."""

    def __init__(
        self,
        stream,
        header: VcfHeader,
        write_header: bool = True,
        append_terminator: bool = False,
    ):
        self.header = bcf.BcfHeader(header)
        self._w = bgzf.BgzfWriter(stream, append_terminator=append_terminator)
        if write_header:
            self._w.write(bcf.encode_header(header))

    def write(self, v) -> None:
        self._w.write(bcf.encode_record(self.header, v))

    def close(self) -> None:
        self._w.close()
