"""BCF input/output: record-aligned split planning, batched reading, writer.

Reference parity:
- ``BcfSplitGuesser``: find a record start inside an arbitrary byte range,
  handling both BGZF and uncompressed BCF, with the reference's candidate
  sanity rules — plausible l_shared/l_indiv, CHROM within the contig
  dictionary, POS/rlen sane, n_sample == header sample count, ID field is a
  typed string — then verification by decoding 2 whole BGZF blocks
  (compressed) or a 0x80000-byte window (uncompressed)
  (BCFSplitGuesser.java:61-75,118-360),
- ``BcfInputFormat``: byte splits fixed up to record starts
  (VCFInputFormat.fixBCFSplits/addGuessedSplits, VCFInputFormat.java:302-385),
- ``BcfRecordWriter``: always-BGZF output with swallowed-header part mode
  (BCFRecordWriter.java:49-178).
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Tuple

import numpy as np

from ..conf import Configuration, VCF_INTERVALS, VCFRECORDREADER_VALIDATION_STRINGENCY
from ..spec import bcf, bgzf
from ..spec.vcf import VcfHeader, variant_key
from ..utils.intervals import Interval, parse_intervals
from . import fs
from .splits import FileVirtualSplit
from .vcf import VariantBatch

# Verification bounds (BCFSplitGuesser.java:61-75).
BGZF_BLOCKS_NEEDED_FOR_GUESS = 2
UNCOMPRESSED_BYTES_NEEDED_FOR_GUESS = 0x80000


class BcfSplitGuesser:
    """Find the first real BCF record start in ``[beg, end)``."""

    def __init__(self, data: bytes, header: bcf.BcfHeader, compressed: Optional[bool] = None):
        self.data = data
        self.header = header
        self.compressed = (
            bgzf.is_bgzf(data) if compressed is None else compressed
        )

    # -- candidate scan (vectorized over every payload offset) --------------

    def _candidate_offsets(self, payload: np.ndarray) -> np.ndarray:
        """Offsets passing the sanity rules (BCFSplitGuesser.java:273-360)."""
        n = len(payload)
        # minimal record: 8-byte lengths + 24-byte fixed shared fields
        if n < 33:
            return np.empty(0, dtype=np.int64)
        count = n - 32
        pad = np.zeros(40, dtype=np.uint8)
        a = np.concatenate([payload, pad])

        def u32(off: int) -> np.ndarray:
            return (
                a[off : off + count].astype(np.uint64)
                | (a[off + 1 : off + count + 1].astype(np.uint64) << 8)
                | (a[off + 2 : off + count + 2].astype(np.uint64) << 16)
                | (a[off + 3 : off + count + 3].astype(np.uint64) << 24)
            )

        l_shared = u32(0)
        l_indiv = u32(4)
        chrom = u32(8).astype(np.int64).astype(np.int32)
        pos = u32(12).astype(np.int64).astype(np.int32)
        rlen = u32(16).astype(np.int64).astype(np.int32)
        nai = u32(24)
        n_allele = (nai >> np.uint64(16)).astype(np.int64)
        nfs = u32(28)
        n_sample = (nfs & np.uint64(0xFFFFFF)).astype(np.int64)

        ok = (l_shared >= 24) & (l_shared < 1 << 24) & (l_indiv < 1 << 28)
        ok &= (chrom >= 0) & (chrom < len(self.header.contigs))
        ok &= (pos >= -1) & (rlen >= 0)
        ok &= n_allele < 0xFFFF
        ok &= n_sample == self.header.n_samples
        # ID field begins right after the fixed 24 shared bytes: its typed
        # descriptor must be a string (char) or missing (:340-352).
        id_desc = a[32 : 32 + count]
        ok &= ((id_desc & 0xF) == bcf.T_CHAR) | (id_desc == 0)
        return np.nonzero(ok)[0].astype(np.int64)

    # -- verification --------------------------------------------------------

    def _decodes_from(self, payload: bytes, p: int, need_bytes: int) -> bool:
        """True iff consecutive records decode from ``p`` until the window is
        exhausted (truncation mid-record after ≥1 success is acceptable)."""
        decoded = 0
        limit = min(len(payload), p + need_bytes)
        while p + 8 <= limit:
            l_shared, l_indiv = struct.unpack_from("<II", payload, p)
            if p + 8 + l_shared + l_indiv > len(payload):
                # Starts in the window but extends past the buffer: truncation
                # is acceptable iff ≥1 record already decoded (:248-263).
                return decoded > 0
            try:
                _, p = bcf.decode_record(payload, p, self.header)
            except (bcf.BcfError, struct.error, IndexError, ValueError, KeyError):
                return False
            decoded += 1
        return decoded > 0

    def guess_next_record_start(self, beg: int, end: int) -> Optional[int]:
        """Virtual offset of the first verifiable record in the byte range
        ``[beg, end)``; None when none found.  Uncompressed files use the
        degenerate ``offset<<16`` voffset form so both kinds flow through the
        same FileVirtualSplit machinery."""
        if self.compressed:
            return self._guess_bgzf(beg, end)
        return self._guess_plain(beg, end)

    def _guess_plain(self, beg: int, end: int) -> Optional[int]:
        window = self.data[
            beg : min(len(self.data), end + UNCOMPRESSED_BYTES_NEEDED_FOR_GUESS)
        ]
        arr = np.frombuffer(window, dtype=np.uint8)
        in_range = self._candidate_offsets(arr)
        for off in in_range:
            if off >= end - beg:
                break
            if self._decodes_from(
                window, int(off), UNCOMPRESSED_BYTES_NEEDED_FOR_GUESS
            ):
                return (beg + int(off)) << 16
        return None

    def _guess_bgzf(self, beg: int, end: int) -> Optional[int]:
        from .. import native

        pos = beg
        while True:
            cp = native.find_next_block(self.data, pos, min(end, len(self.data)))
            if cp < 0 or cp >= end:
                return None
            # Inflate this block + enough successors for verification.
            co, cs_l, us_l = [], [], []
            p = cp
            while len(co) < BGZF_BLOCKS_NEEDED_FOR_GUESS + 2 and p < len(self.data):
                try:
                    csize, usize = bgzf.read_block_at(self.data, p)
                except bgzf.BgzfError:
                    break
                co.append(p)
                cs_l.append(csize)
                us_l.append(usize)
                p += csize
            if co:
                try:
                    out, offs = native.inflate_blocks(
                        self.data,
                        np.asarray(co, dtype=np.int64),
                        np.asarray(cs_l, dtype=np.int32),
                        np.asarray(us_l, dtype=np.int32),
                    )
                    payload = out.tobytes()
                    first_len = int(offs[1] - offs[0]) if len(offs) > 1 else len(payload)
                    cands = self._candidate_offsets(
                        np.frombuffer(payload[:first_len], dtype=np.uint8)
                    )
                    for up in cands:
                        if self._decodes_from(
                            payload,
                            int(up),
                            sum(us_l[:BGZF_BLOCKS_NEEDED_FOR_GUESS]),
                        ):
                            return (cp << 16) | int(up)
                except bgzf.BgzfError:
                    pass
            pos = cp + 1


def read_bcf_header(
    data: bytes, compressed: Optional[bool] = None
) -> Tuple[bcf.BcfHeader, int]:
    """(header, offset of first record in the *uncompressed* stream),
    inflating only as many leading blocks as the header occupies."""
    if compressed is None:
        compressed = bgzf.is_bgzf(data)
    if not compressed:
        return bcf.decode_header(data)
    chunk = bytearray()
    pos = 0
    while pos < len(data):
        payload, csize = bgzf.inflate_block(data, pos)
        chunk.extend(payload)
        pos += csize
        if len(chunk) >= 9:
            (l_text,) = struct.unpack_from("<I", chunk, 5)
            if len(chunk) >= 9 + l_text:
                break
    return bcf.decode_header(bytes(chunk))


class BcfInputFormat:
    """BCF split planning + batched reading (VCFInputFormat BCF arm)."""

    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()

    def _stringency(self) -> str:
        s = (
            self.conf.get(VCFRECORDREADER_VALIDATION_STRINGENCY, "STRICT")
            or "STRICT"
        ).upper()
        return s

    def _intervals(self) -> Optional[List[Interval]]:
        return parse_intervals(self.conf.get(VCF_INTERVALS))

    def get_splits(
        self, paths, split_size: int = 4 << 20
    ) -> List[FileVirtualSplit]:
        """Byte ranges fixed up to record starts with the guesser
        (VCFInputFormat.java:302-385).  Virtual offsets for BGZF files, plain
        ``offset<<16`` voffsets for uncompressed ones so one split type serves
        both (the reference uses FileVirtualSplit the same way)."""
        out: List[FileVirtualSplit] = []
        for path in sorted(paths):
            # Planning reads the file once through the seam (the guesser
            # needs verify windows across it — the client-side cost the
            # reference's BCFSplitGuesser pays too).
            data = fs.get_fs(path).read_all(path)
            compressed = bgzf.is_bgzf(data)
            hdr, first = read_bcf_header(data, compressed)
            guesser = BcfSplitGuesser(data, hdr, compressed)
            size = len(data)
            bounds = list(range(0, size, split_size)) + [size]
            starts: List[int] = []
            for beg in bounds[:-1]:
                g = guesser.guess_next_record_start(beg, min(beg + split_size, size))
                if g is not None:
                    starts.append(g)
            # First record of the file is authoritative for split 0.
            if compressed:
                acc = 0
                v0 = 0
                for b in bgzf.scan_blocks(data):
                    if first < acc + b.usize:
                        v0 = bgzf.make_voffset(b.coffset, first - acc)
                        break
                    acc += b.usize
            else:
                v0 = first << 16
            starts = sorted(set([v0] + [s for s in starts if s > v0]))
            vend = (size << 16) | 0xFFFF if compressed else size << 16
            for i, s in enumerate(starts):
                e = starts[i + 1] if i + 1 < len(starts) else vend
                if e > s:
                    out.append(FileVirtualSplit(path, s, e))
        return out

    def read_split(
        self, split: FileVirtualSplit, data: Optional[bytes] = None
    ) -> VariantBatch:
        stringency = self._stringency()
        intervals = self._intervals()
        if data is None:
            # Split-local: the header comes from a growing prefix read and
            # the record range from its own byte window — a split costs
            # O(header + split), not O(file).  Split ends are record-start
            # voffsets (the planner's contract), so no record spills past
            # the window's end-block margin.
            hdr, payload, p, end = _read_bcf_split_local(split)
        else:
            compressed = bgzf.is_bgzf(data)
            if compressed:
                payload, p, end = _inflate_range(
                    data, split.vstart, split.vend
                )
            else:
                payload = data
                p = split.vstart >> 16
                end = split.vend >> 16
            hdr, _ = read_bcf_header(data, compressed)
        fast = _read_vectorized(payload, p, end, hdr, intervals)
        if fast is not None:
            return fast
        variants: List[bcf.BcfVariant] = []
        while p + 8 <= end:
            try:
                v, p = bcf.decode_record(payload, p, hdr)
            except (bcf.BcfError, struct.error, IndexError, ValueError, KeyError):
                if stringency == "STRICT":
                    raise
                break
            if intervals is not None and not any(
                iv.overlaps(v.chrom, v.start, v.end) for iv in intervals
            ):
                continue
            variants.append(v)
        keys = np.array(
            [variant_key(hdr.vcf, v) for v in variants], dtype=np.int64
        )
        pos = np.array([v.pos for v in variants], dtype=np.int64)
        endp = np.array([v.end for v in variants], dtype=np.int64)
        return VariantBatch(
            header=hdr.vcf, variants=variants, keys=keys, pos=pos, end=endp
        )


def _read_vectorized(
    payload, p: int, end: int, hdr: bcf.BcfHeader, intervals
) -> Optional[VariantBatch]:
    """Batched BCF split decode (VERDICT r3 #4): one serial chain walk
    finds every record boundary, the fixed-width shared prefix decodes as
    NumPy gathers over the whole payload, and the 64-bit keys and
    pos/end columns come out as array ops — no per-record Python.  The
    ``VariantContext`` rows stay lazy (``bcf.decode_record`` runs only if
    a consumer asks — the LazyBCFGenotypesContext stance one level up).

    Returns None when anything needs the exact per-record path — a
    truncated/misaligned chain, a CHROM outside the dictionaries, any
    typed value the C validator cannot prove the exact decoder would
    accept (bad type codes, out-of-range dictionary indexes, shared-block
    length mismatches, ambiguous INFO END) — so the exact parser's error
    semantics (incl. STRICT stringency raises) stay the contract."""
    from .. import native

    a = (
        payload
        if isinstance(payload, np.ndarray)
        else np.frombuffer(payload, np.uint8)
    )
    if not native.available():
        return None  # the chain walk is serial: C or nothing
    try:
        end_key = hdr.strings.index("END") if "END" in hdr.strings else -1
        offs, ref_len, end_info = native.bcf_scan(
            a, p, end, len(hdr.contigs), len(hdr.strings), end_key
        )
    except ValueError:
        return None
    n = len(offs)
    if n == 0:
        return VariantBatch(header=hdr.vcf, variants=[])

    def i32(at: np.ndarray) -> np.ndarray:
        return (
            a[at].astype(np.uint32)
            | (a[at + 1].astype(np.uint32) << 8)
            | (a[at + 2].astype(np.uint32) << 16)
            | (a[at + 3].astype(np.uint32) << 24)
        ).astype(np.int32)

    body = offs + 8
    chrom_i = i32(body)
    pos0 = i32(body + 4).astype(np.int64)
    # BCF contig order need not match the VCF header's contig-line order
    # (IDX= overrides): map through the VCF dictionary once per contig
    # (contig_index never raises — unknown names get the murmur3 key).
    vmap = np.empty(len(hdr.contigs), dtype=np.int64)
    for ci, name in enumerate(hdr.contigs):
        vmap[ci] = hdr.vcf.contig_index(name)
    idx = vmap[chrom_i]
    # variant_key semantics including the Java sign-extension quirk: a
    # negative (pos-1) floods the high word (POS=0 telomeric records).
    keys = (idx << 32) | np.where(pos0 < 0, pos0, pos0 & 0xFFFFFFFF)
    pos1 = pos0 + 1
    # end: INFO END when present (the exact path's END= regex), else
    # pos + len(REF) - 1 — both extracted by the C scan.
    endp = np.where(
        end_info != np.iinfo(np.int64).min, end_info, pos0 + ref_len
    )

    if intervals is not None:
        name_to_ci = {name: ci for ci, name in enumerate(hdr.contigs)}
        keep = np.zeros(n, dtype=bool)
        for iv in intervals:
            ci = name_to_ci.get(iv.contig)
            if ci is None:
                continue
            keep |= (
                (chrom_i == ci) & (pos1 <= iv.end) & (endp >= iv.start)
            )
        offs, keys, pos1, endp = (
            offs[keep], keys[keep], pos1[keep], endp[keep]
        )

    kept = offs

    def materialize() -> List[bcf.BcfVariant]:
        out: List[bcf.BcfVariant] = []
        for o in kept:
            v, _ = bcf.decode_record(payload, int(o), hdr)
            out.append(v)
        return out

    return VariantBatch(
        header=hdr.vcf,
        keys=keys,
        pos=pos1,
        end=endp,
        materializer=materialize,
    )


def _read_bcf_header_prefix(path: str):
    """(header, compressed?) via growing prefix reads — O(header) bytes."""
    f = fs.get_fs(path)
    size = f.size(path)
    n = 8 << 10
    while True:
        prefix = f.read_range(path, 0, min(n, size))
        compressed = bgzf.is_bgzf(prefix)
        try:
            hdr, _ = read_bcf_header(prefix, compressed)
            return hdr, compressed
        except (bcf.BcfError, bgzf.BgzfError, struct.error, IndexError):
            if n >= size:
                raise
            n *= 4


def _read_bcf_split_local(split: FileVirtualSplit):
    """(header, payload, start, record-start limit) reading only the
    split's byte window + a growing header prefix."""
    hdr, compressed = _read_bcf_header_prefix(split.path)
    f = fs.get_fs(split.path)
    if compressed:
        c0 = split.vstart >> 16
        c1 = split.vend >> 16
        # The end block's full extent (≤64KiB) plus slack.
        window = f.read_range(split.path, c0, (c1 - c0) + 0x20000)
        shift = c0 << 16
        payload, p, end = _inflate_range(
            window, split.vstart - shift, split.vend - shift
        )
        return hdr, payload, p, end
    p = split.vstart >> 16
    end = split.vend >> 16
    window = f.read_range(split.path, p, end - p)
    return hdr, window, 0, end - p


def _inflate_range(data: bytes, vstart: int, vend: int) -> Tuple[bytes, int, int]:
    """Inflate the BGZF blocks covering [vstart, vend) → (payload, start
    offset, record-start limit).  Records *start* strictly before the limit;
    the tail block at vend's coffset is included so a record straddling the
    boundary completes (the BGZFLimitingStream role,
    BCFRecordReader.java:176-236)."""
    c0, u0 = bgzf.split_voffset(vstart)
    c1, u1 = bgzf.split_voffset(vend)
    chunks: List[bytes] = []
    pos = c0
    acc_before_end_block = None
    while pos < len(data) and pos <= c1:
        if pos == c1:
            acc_before_end_block = sum(len(c) for c in chunks)
        try:
            payload, csize = bgzf.inflate_block(data, pos)
        except bgzf.BgzfError:
            break
        chunks.append(payload)
        pos += csize
    blob = b"".join(chunks)
    limit = (
        len(blob)
        if acc_before_end_block is None
        else min(acc_before_end_block + u1, len(blob))
    )
    return blob, u0, limit


class BcfRecordWriter:
    """Always-BGZF BCF writer with headerless part mode
    (BCFRecordWriter.java:49-138)."""

    def __init__(
        self,
        stream,
        header: VcfHeader,
        write_header: bool = True,
        append_terminator: bool = False,
    ):
        self.header = bcf.BcfHeader(header)
        self._w = bgzf.BgzfWriter(stream, append_terminator=append_terminator)
        if write_header:
            self._w.write(bcf.encode_header(header))

    def write(self, v) -> None:
        self._w.write(bcf.encode_record(self.header, v))

    def close(self) -> None:
        self._w.close()
