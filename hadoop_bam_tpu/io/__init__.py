"""Host-side I/O: split planning, record readers/writers, mergers.

This is the TPU build's equivalent of reference layers L3-L5 and L7: the
file-format intelligence stays on the host (cheap, irregular); the readers
produce batched structure-of-arrays tensors for the device pipeline instead
of per-record iterators.
"""

from .splits import FileVirtualSplit  # noqa: F401
from .guesser import BamSplitGuesser  # noqa: F401
from .bam import BamInputFormat, BamOutputWriter  # noqa: F401
from .merger import merge_bam_parts  # noqa: F401
