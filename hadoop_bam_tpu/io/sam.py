"""SAM text input format: split reading with header re-injection.

Reference semantics (SAMRecordReader.java): text byte splits with the
skip-first-line / read-past-end protocol (:108-146); mid-file splits parse
records against the header read from the file start (the role of
WorkaroundingStream's header re-injection, :183-330 — data lines can never
start with ``@`` since QNAME's alphabet excludes it, so header skipping is
line-deterministic).  Compressed SAM is unsplittable.

Output: SAMRecordWriter equivalent (text writer, sort order from header).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..conf import Configuration
from ..spec import bam, sam
from .bam import RecordBatch
from .splits import ByteSplit
from .text import (
    SplitLineReader,
    plan_byte_splits,
    read_header_prefix,
    read_split_window,
)


class SamInputFormat:
    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()

    def get_splits(self, paths, split_size: int = 4 << 20) -> List[ByteSplit]:
        out: List[ByteSplit] = []
        for p in sorted(paths):
            out.extend(plan_byte_splits(p, split_size))
        return out

    def read_header(self, path: str, data: Optional[bytes] = None) -> bam.BamHeader:
        if data is None:
            data = read_header_prefix(path, b"@")
        lines = []
        pos = 0
        while pos < len(data):
            nl = data.find(b"\n", pos)
            line = data[pos : nl if nl >= 0 else len(data)]
            if not line.startswith(b"@"):
                break
            lines.append(line.decode().rstrip("\r"))
            if nl < 0:
                break
            pos = nl + 1
        hdr, _ = sam.read_sam("\n".join(lines) + "\n")
        return hdr

    def read_split(
        self, split: ByteSplit, data: Optional[bytes] = None
    ) -> RecordBatch:
        if data is None:
            # Split-local read: only this split's byte window comes off the
            # filesystem (SAMRecordReader.java:108-146 protocol); the header
            # is re-read from the file head and injected — the
            # WorkaroundingStream role (:183-330).  Gzip falls back to the
            # whole decompressed payload (unsplittable, single split).
            data, split = read_split_window(split)
            header = (
                self.read_header(split.path, data=data)
                if split.start == 0  # window starts at the file head
                else self.read_header(split.path)
            )
        else:
            header = self.read_header(split.path, data=data)
        # Vectorized fast path: the whole split tokenizes as array ops and
        # emits the binary blob directly (byte-identical to the per-line
        # encode); anything it cannot prove well-formed falls back to the
        # exact per-line parser, whose error messages are the contract.
        from .sam_vec import parse_split_vectorized

        blob_arr = parse_split_vectorized(
            np.frombuffer(data, np.uint8)
            if not isinstance(data, np.ndarray)
            else data,
            split.start,
            split.end,
            header,
        )
        if blob_arr is not None:
            return _blob_to_batch(blob_arr)
        reader = SplitLineReader(data, split.start, split.end)
        records: List[bam.BamRecord] = []
        for _, line in reader.lines():
            if not line or line.startswith(b"@"):
                continue
            records.append(sam.sam_line_to_record(line.decode(), header))
        return _records_to_batch(records)


def _records_to_batch(records: List[bam.BamRecord]) -> RecordBatch:
    """Binary-encode parsed records and run the standard SoA decode, so SAM
    text feeds the identical device pipeline as BAM."""
    blob = b"".join(r.encode() for r in records)
    return _blob_to_batch(np.frombuffer(blob, np.uint8))


def _blob_to_batch(arr: np.ndarray) -> RecordBatch:
    offsets = (
        bam.record_offsets(arr, 0) if len(arr) else np.empty(0, np.int64)
    )
    soa = (
        bam.soa_decode(arr, offsets)
        if len(offsets)
        else {k: np.empty(0, np.int64) for k in bam.SOA_FIELDS}
    )
    keys = bam.soa_keys(soa, arr) if len(offsets) else np.empty(0, np.int64)
    return RecordBatch(soa=soa, data=arr, keys=keys)


class SamOutputWriter:
    """Text SAM writer (SAMRecordWriter.java:84-104 semantics)."""

    def __init__(self, stream, header: bam.BamHeader, write_header: bool = True):
        self._stream = stream
        self.header = header
        if write_header and header.text:
            stream.write((header.text.rstrip("\n") + "\n").encode())

    def write_record(self, rec: bam.BamRecord) -> None:
        self._stream.write(
            (sam.record_to_sam_line(rec, self.header) + "\n").encode()
        )

    def write_batch(self, batch: RecordBatch, order=None) -> None:
        idx = range(batch.n_records) if order is None else order
        for i in idx:
            self.write_record(batch.record(int(i)))

    def close(self) -> None:
        pass
