"""BAM input format: record-aligned split planning + batched split reading.

Reference parity (BAMInputFormat.java):
- three-tier split planning: `.splitting-bai` index → [BAI linear index] →
  heuristic guesser fallback (getSplits, :216-260; fallback chain :244-258),
- indexed snapping via nextAlignment/prevAlignment with the last split's end
  forced to ``… | 0xffff`` (:284-303),
- recordless probabilistic splits merged backward, error if first
  (:497-525),
- interval-bounded traversal via BAI chunk spans (:532-634) and
  unmapped-only splits (:609-631).

TPU-first difference: a split is read as one *batch* — all its BGZF blocks
are inflated with the native thread pool, the record chain is walked once,
and the result is a structure-of-arrays RecordBatch ready to ship to device —
instead of the reference's per-record iterator.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .. import native
from . import fs
from ..conf import (
    BAM_BOUNDED_TRAVERSAL,
    BAM_ENABLE_BAI_SPLITTER,
    BAM_INTERVALS,
    BAM_TRAVERSE_UNPLACED_UNMAPPED,
    BAM_WRITE_SPLITTING_BAI,
    ERRORS_MODE,
    Configuration,
)
from ..spec import bam, bgzf, indices
from ..utils.hbm import LEDGER
from ..utils.intervals import Interval, parse_intervals
from ..utils.tracing import METRICS, span
from .guesser import BamSplitGuesser
from .splits import FileVirtualSplit

SPLITTING_BAI_EXT = indices.SPLITTING_BAI_EXT
DEFAULT_SPLIT_SIZE = 4 << 20


@dataclass
class RecordBatch:
    """A decoded split: SoA fixed fields + ragged byte sideband + keys.

    ``data`` holds the uncompressed record stream for this batch; per-record
    bodies live at ``soa['rec_off'] .. +soa['rec_len']`` (the lazy sideband).

    ``device_data``, when set, is a device-resident (jax) uint8 copy of the
    same byte window, left in HBM by the lockstep-lane inflate tier (the
    on-chip output-residency handoff): the device-parse path feeds it to
    the chain kernel directly instead of re-uploading ``data``.  It is
    only attached when byte-for-byte identical to ``data``.
    """

    soa: dict
    data: np.ndarray  # uint8
    keys: np.ndarray  # int64
    device_data: Optional[object] = None  # jax uint8, same bytes as data

    @property
    def n_records(self) -> int:
        # rec_off is present in every field subset; keys may be skipped
        # entirely (with_keys=False, e.g. the device-parse sort path).
        off = self.soa.get("rec_off")
        return len(off) if off is not None else len(self.keys)

    def record(self, i: int) -> bam.BamRecord:
        off = int(self.soa["rec_off"][i])
        ln = int(self.soa["rec_len"][i])
        body = self.data[off : off + ln].tobytes()
        rec, _ = bam.decode_record(
            struct.pack("<I", ln) + body, 0
        )
        return rec

    def records(self) -> Iterator[bam.BamRecord]:
        for i in range(self.n_records):
            yield self.record(i)


#: The column subset the sort pipeline needs: key inputs + record extents.
SORT_FIELDS = ("refid", "pos", "flag", "rec_off", "rec_len")


@dataclass
class ChunkedRecords:
    """A zero-copy view over several RecordBatches as one logical batch.

    Where :func:`~hadoop_bam_tpu.pipeline._concat_batches` copies every
    split's payload into one buffer, this keeps the per-split buffers and
    addresses records by ``(chunk_id, rec_off)``; the permuted write gather
    reads straight from the original buffers (native
    ``hbam_gather_records_chunked``).  ``soa`` carries only
    ``rec_off``/``rec_len`` — by the time a chunked view exists the keys are
    computed and the other fixed fields are dead."""

    chunks: List[np.ndarray]  # per-split uint8 payloads
    chunk_id: np.ndarray  # int32 per record
    soa: dict  # {"rec_off": int64 (chunk-local body offs), "rec_len": int64}
    keys: Optional[np.ndarray] = None  # int64; None when keys live on-device
    _validated: bool = False  # extent bounds checked once, then trusted
    #: Device-resident flat copy of the concatenated chunk payloads (jax
    #: uint8), present only when EVERY source batch carried
    #: ``device_data`` and the caller asked to keep it — the
    #: device-resident write path gathers parts straight from it.
    device_flat: Optional[object] = None
    chunk_base: Optional[np.ndarray] = None  # int64 chunk offsets in flat

    @property
    def n_records(self) -> int:
        return len(self.soa["rec_off"])

    def release_device(self) -> None:
        """Drop the HBM-resident flat payload so it frees once the part
        writes are done (the write-path residency lifetime).  The
        explicit ledger release is the audited event — skipping it is
        exactly the leak shape the ledger's drill re-creates."""
        if self.device_flat is not None:
            LEDGER.release(self.device_flat)
        self.device_flat = None
        self.chunk_base = None

    @classmethod
    def from_batches(
        cls,
        batches: Sequence[RecordBatch],
        with_keys: bool = True,
        keep_device: bool = False,
    ) -> "ChunkedRecords":
        if not batches:
            return cls(
                chunks=[],
                chunk_id=np.empty(0, np.int32),
                soa={
                    "rec_off": np.empty(0, np.int64),
                    "rec_len": np.empty(0, np.int64),
                },
                keys=np.empty(0, np.int64) if with_keys else None,
            )
        chunk_id = np.concatenate(
            [
                np.full(b.n_records, i, dtype=np.int32)
                for i, b in enumerate(batches)
            ]
        )
        device_flat = None
        chunk_base = None
        if keep_device and all(
            b.device_data is not None for b in batches
        ):
            # One device-to-device concat up front: the per-split buffers
            # can then free (callers drop their ``device_data`` refs) and
            # every part write gathers from this single resident stream.
            # Built eagerly so concurrent part writers never race a lazy
            # concat.
            try:
                parts = [b.device_data for b in batches]
                if len(parts) == 1:
                    # Ownership handoff, no copy: the split window IS the
                    # write stream now.
                    device_flat = LEDGER.transfer(
                        parts[0], "bam.write_flat", kind="write_stream"
                    )
                else:
                    # Device-to-device concat adopts the donors: their
                    # per-split windows close cleanly in the ledger and
                    # the flat stream carries the residency forward.
                    # The concat *donates* the windows (the DeviceStream
                    # windows→write-stream seam), so on donation-capable
                    # backends HBM holds the windows or the flat stream
                    # — never both — during the write-phase setup.
                    from ..device_stream import donating_concat

                    device_flat = LEDGER.adopt(
                        donating_concat(parts),
                        kind="write_stream",
                        holder="bam.write_flat",
                        donors=parts,
                    )
                chunk_base = np.cumsum(
                    [0] + [len(b.data) for b in batches[:-1]]
                ).astype(np.int64)
                METRICS.count("bam.write_residency_kept", 1)
            except Exception:
                device_flat = None
                chunk_base = None
        return cls(
            chunks=[b.data for b in batches],
            chunk_id=chunk_id,
            soa={
                "rec_off": np.concatenate(
                    [b.soa["rec_off"] for b in batches]
                ),
                "rec_len": np.concatenate(
                    [b.soa["rec_len"] for b in batches]
                ),
            },
            keys=(
                np.concatenate([b.keys for b in batches])
                if with_keys
                else None
            ),
            device_flat=device_flat,
            chunk_base=chunk_base,
        )


def splitting_bai_path(path: str) -> str:
    return path + SPLITTING_BAI_EXT


class BamInputFormat:
    """Split planning + split reading for BAM files."""

    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()
        self._device_inflate_cached: Optional[bool] = None
        self._nrefs_cache: dict = {}

    def errors_mode(self) -> str:
        """The configured error policy: 'strict' (default) or 'salvage'
        (the ``hadoopbam.errors`` conf key)."""
        return self.conf.get(ERRORS_MODE, "strict") or "strict"

    def _nrefs(self, path: str) -> int:
        """Header reference count, cached per path — the salvage reader's
        record-resync sanity rules need it."""
        if path not in self._nrefs_cache:
            self._nrefs_cache[path] = read_header(path).n_refs
        return self._nrefs_cache[path]

    def _device_inflate(self) -> bool:
        """Route split inflate through the lockstep-lane device tier?
        Conf/env/auto-rule resolution is in ``ops.flate.lanes_tier_enabled``
        (imported lazily — split *planning* must not pull in jax)."""
        if self._device_inflate_cached is None:
            from ..ops.flate import lanes_tier_enabled

            self._device_inflate_cached = lanes_tier_enabled(self.conf)
        return self._device_inflate_cached

    # -- planning -----------------------------------------------------------

    def get_splits(
        self,
        paths: Sequence[str],
        split_size: int = DEFAULT_SPLIT_SIZE,
    ) -> List[FileVirtualSplit]:
        splits: List[FileVirtualSplit] = []
        for path in sorted(paths):
            splits.extend(self._splits_for_file(path, split_size))
        intervals = self._traversal_intervals()
        unmapped_only = self.conf.get_boolean(BAM_TRAVERSE_UNPLACED_UNMAPPED)
        if intervals is not None or (
            unmapped_only and self.conf.get_boolean(BAM_BOUNDED_TRAVERSAL)
        ):
            splits = self.filter_by_interval(splits, intervals, unmapped_only)
        return splits

    def _traversal_intervals(self) -> Optional[List[Interval]]:
        if not self.conf.get_boolean(BAM_BOUNDED_TRAVERSAL):
            return None
        return parse_intervals(self.conf.get(BAM_INTERVALS))

    def _splits_for_file(
        self, path: str, split_size: int
    ) -> List[FileVirtualSplit]:
        size = fs.get_fs(path).size(path)
        byte_splits = [
            (s, min(s + split_size, size)) for s in range(0, size, split_size)
        ]
        if not byte_splits:
            return []
        idx_path = splitting_bai_path(path)
        if fs.get_fs(idx_path).exists(idx_path):
            try:
                idx = indices.SplittingBai.load(
                    fs.get_fs(idx_path).read_all(idx_path)
                )
                # Stale/corrupt index detection beyond the reference's ordering
                # check: the terminator must encode this file's actual size.
                if idx.bam_size() != size:
                    raise IOError("splitting-bai does not match file size")
                return self._indexed_splits(path, byte_splits, idx)
            except IOError:
                pass  # bad index → regenerate probabilistically (:305-308)
        if self.conf.get_boolean(BAM_ENABLE_BAI_SPLITTER):
            bai_path = _find_bai(path)
            if bai_path is not None:
                try:
                    bai = indices.Bai.load(bai_path)
                    return self._bai_splits(path, byte_splits, bai)
                except IOError:
                    pass  # unreadable .bai → fall through to the guesser
        return self._probabilistic_splits(path, byte_splits)

    def _bai_splits(
        self,
        path: str,
        byte_splits: List[Tuple[int, int]],
        bai: indices.Bai,
    ) -> List[FileVirtualSplit]:
        """Tier-2 planning from the linear `.bai` index
        (BAMInputFormat.addBAISplits, BAMInputFormat.java:322-465).

        The linear index stores, per 16kb genome window, the smallest virtual
        offset of any record overlapping it; every such offset is a known
        record boundary.  Splits snap to the first boundary at/after their
        byte start; a split with no boundary inside it falls back to the
        heuristic guesser (the reference's :432-445 behaviour).  Start
        offsets computed this way are contiguous — each split's end is the
        next split's start, and the last extends past EOF — so every record
        is read exactly once, including the unmapped tail.
        """
        voffs: List[int] = []
        for rid in range(len(bai.refs)):
            voffs.extend(v for v in bai.linear_index(rid) if v > 0)
        first = bai.first_offset()
        if first is not None:
            voffs.append(first)
        if not voffs:
            raise IOError("empty .bai: no linear index entries")
        varr = np.unique(np.asarray(voffs, dtype=np.int64))
        coffs = varr >> 16  # compressed file offsets of the boundaries
        size = byte_splits[-1][1]
        if int(coffs[-1]) >= size:
            # Stale/mismatched index: a boundary points past EOF (the
            # splitting-bai tier's bam_size() guard equivalent).
            raise IOError(".bai does not match file: offset past EOF")
        end_sentinel = (size << 16) | 0xFFFF

        guesser: Optional[BamSplitGuesser] = None
        file_data: Optional[bytes] = None
        starts: List[int] = []
        for j, (start, end) in enumerate(byte_splits):
            if j == 0:
                # First split starts at the first record, header skipped
                # (the reference's getFilePointerSpanningReads, :115-123).
                _, vfirst = read_header_voffset(path)
                starts.append(vfirst)
                continue
            k = int(np.searchsorted(coffs, start, side="left"))
            if k < len(varr) and coffs[k] < end:
                starts.append(int(varr[k]))
                continue
            # No indexed boundary in this split: guess (:432-445).  The
            # guesser needs raw bytes — load the file once, lazily.
            if guesser is None:
                if file_data is None:
                    file_data = fs.get_fs(path).read_all(path)
                hdr, _ = _read_header(file_data)
                guesser = BamSplitGuesser(file_data, hdr.n_refs)
            g = guesser.guess_next_record_start(start, end)
            if g != end:
                starts.append(g)
            else:
                # Miss: take the next indexed boundary at/after ``end`` so
                # ``starts`` stays monotone (a raw (end<<16)|0xffff sentinel
                # could exceed the next split's snapped start and make
                # adjacent splits overlap → records read twice).
                starts.append(int(varr[k]) if k < len(varr) else end_sentinel)

        out: List[FileVirtualSplit] = []
        for j, vstart in enumerate(starts):
            vend = starts[j + 1] if j + 1 < len(starts) else end_sentinel
            if vstart < vend:
                out.append(FileVirtualSplit(path, vstart, vend))
        if not out:
            raise IOError(f"'{path}': no reads found via .bai splitter")
        return out

    def _indexed_splits(
        self,
        path: str,
        byte_splits: List[Tuple[int, int]],
        idx: indices.SplittingBai,
    ) -> List[FileVirtualSplit]:
        if idx.size() == 1:
            return []  # no alignments (BAMInputFormat.java:281-283)
        out: List[FileVirtualSplit] = []
        for j, (start, end) in enumerate(byte_splits):
            vstart = idx.next_alignment(start)
            if j == len(byte_splits) - 1:
                prev = idx.prev_alignment(end)
                vend = None if prev is None else prev | 0xFFFF
            else:
                vend = idx.next_alignment(end)
            if vstart is None or vend is None:
                # Index didn't cover the range (BAMInputFormat.java:305-308).
                return self._probabilistic_splits(path, byte_splits)
            if vstart >= vend:
                continue  # empty split (no record begins in it)
            out.append(FileVirtualSplit(path, vstart, vend))
        return out

    def _probabilistic_splits(
        self, path: str, byte_splits: List[Tuple[int, int]]
    ) -> List[FileVirtualSplit]:
        data = fs.get_fs(path).read_all(path)
        hdr, _ = _read_header(data)
        guesser = BamSplitGuesser(data, hdr.n_refs)
        out: List[FileVirtualSplit] = []
        for beg, end in byte_splits:
            aligned_beg = guesser.guess_next_record_start(beg, end)
            aligned_end = (end << 16) | 0xFFFF
            if aligned_beg == end:
                if not out:
                    raise IOError(
                        f"'{path}': no reads in first split: bad BAM file or "
                        "tiny split size?"
                    )
                out[-1].vend = aligned_end
            else:
                out.append(FileVirtualSplit(path, aligned_beg, aligned_end))
        return out

    # -- interval filtering (BAMInputFormat.java:532-634) -------------------

    def filter_by_interval(
        self,
        splits: List[FileVirtualSplit],
        intervals: Optional[List[Interval]],
        traverse_unplaced_unmapped: bool = False,
    ) -> List[FileVirtualSplit]:
        out: List[FileVirtualSplit] = []
        by_path: dict = {}
        for s in splits:
            by_path.setdefault(s.path, []).append(s)
        for path, file_splits in by_path.items():
            bai_path = _find_bai(path)
            hdr = read_header(path)
            if bai_path is None:
                # Self-reliant fallback: derive the index (needs the bytes).
                bai = indices.build_bai(fs.get_fs(path).read_all(path))
            else:
                bai = indices.Bai.load(
                    fs.get_fs(bai_path).read_all(bai_path)
                )
            chunks: List[indices.Chunk] = []
            if intervals:
                for iv in intervals:
                    try:
                        rid = hdr.ref_index(iv.contig)
                    except KeyError:
                        continue
                    chunks.extend(bai.query(rid, iv.start - 1, iv.end))
            unmapped_start = bai.unmapped_span_start()
            for s in file_splits:
                overlapping = [
                    (max(c.beg, s.vstart), min(c.end, s.vend))
                    for c in chunks
                    if c.beg < s.vend and c.end > s.vstart
                ]
                if overlapping:
                    out.append(
                        FileVirtualSplit(s.path, s.vstart, s.vend, overlapping)
                    )
            if traverse_unplaced_unmapped and unmapped_start is not None:
                # Additive pass, independent of interval hits: the unmapped
                # tail rides in its own split(s) (BAMInputFormat.java:609-631).
                for s in file_splits:
                    if s.vend > unmapped_start:
                        out.append(
                            FileVirtualSplit(
                                s.path,
                                max(s.vstart, unmapped_start),
                                s.vend,
                                None,
                            )
                        )
        return out

    # -- reading ------------------------------------------------------------

    def read_split(
        self,
        split: FileVirtualSplit,
        data: Optional[bytes] = None,
        with_keys: bool = True,
        threads: Optional[int] = None,
        fields: Optional[Sequence[str]] = None,
        device_inflate: Optional[bool] = None,
        inflate_fn=None,
        errors: Optional[str] = None,
        stream=None,
    ) -> RecordBatch:
        """Inflate the split's blocks and decode all its records as one batch.

        Without preloaded ``data``, only the split's byte window (plus a
        spill margin for straddling records) is read from disk — a 100GB BAM
        costs each split only its own bytes.  ``fields`` restricts the SoA
        decode (see :func:`spec.bam.soa_decode`); pass
        :data:`SORT_FIELDS` when only keys + record extents are needed.

        ``device_inflate`` (default: the ``hadoopbam.inflate.lanes`` conf
        key / local-latency auto rule via ``ops.flate.lanes_tier_enabled``)
        ships the split's blocks to the accelerator compressed and inflates
        them on the lockstep-lane tier instead of host zlib.

        ``inflate_fn`` overrides the member inflate entirely (see
        :func:`read_virtual_range`) — the serve daemon's cross-request
        lane batcher plugs in here.

        ``errors`` (default: the ``hadoopbam.errors`` conf key) selects
        the policy on corrupt input: 'strict' raises (pre-PR-7 behavior),
        'salvage' quarantines corrupt BGZF members and unparseable
        records, re-syncs the record chain, and returns what survived
        (``salvage.*`` counters account for the losses).

        ``stream`` (a :class:`~hadoop_bam_tpu.device_stream.DeviceStream`)
        makes this read a stream client: the member inflate rides the
        stream's resolved tier policy (one gate decision per job, with
        the pipelined auto-rtt relaxation) and the residency handoff goes
        through the stream's ledger seam."""
        if device_inflate is None:
            device_inflate = (
                stream.policy.inflate_lanes
                if stream is not None
                else self._device_inflate()
            )
        if errors is None:
            errors = self.errors_mode()
        n_refs = self._nrefs(split.path) if errors == "salvage" else None
        if data is not None:
            return read_virtual_range(
                data,
                split.vstart,
                split.vend,
                with_keys=with_keys,
                threads=threads,
                interval_chunks=split.interval_chunks,
                fields=fields,
                device_inflate=device_inflate,
                inflate_fn=inflate_fn,
                errors=errors,
                n_refs=n_refs,
                stream=stream,
            )
        sfs = fs.get_fs(split.path)
        size = sfs.size(split.path)
        cstart = min(split.vstart >> 16, size)
        cend = min(split.vend >> 16, size)
        margin = 4 << 20
        while True:
            end_byte = min(cend + margin, size)
            with span("bam.stage.read", category="stage"):
                window = fs.read_range_retry(
                    sfs, split.path, cstart, end_byte - cstart
                )
            at_eof = end_byte >= size
            shift = cstart << 16
            chunks = None
            if split.interval_chunks is not None:
                chunks = [
                    (max(b - shift, 0), e - shift)
                    for b, e in split.interval_chunks
                ]
            try:
                return read_virtual_range(
                    window,
                    split.vstart - shift,
                    split.vend - shift,
                    with_keys=with_keys,
                    threads=threads,
                    interval_chunks=chunks,
                    fields=fields,
                    device_inflate=device_inflate,
                    inflate_fn=inflate_fn,
                    errors=errors,
                    n_refs=n_refs,
                    window_at_eof=at_eof,
                    stream=stream,
                )
            except (bam.BamError, bgzf.BgzfError):
                if at_eof:
                    raise
                margin *= 4  # record/block spilled past the window: widen


def _find_bai(path: str) -> Optional[str]:
    """Locate the companion `.bai` (htsjdk SamFiles.findIndex convention:
    ``x.bam.bai`` or ``x.bai``)."""
    for cand in (path + ".bai", os.path.splitext(path)[0] + ".bai"):
        if fs.get_fs(cand).exists(cand):
            return cand
    return None


def _read_header(data: bytes) -> Tuple[bam.BamHeader, int]:
    """Header + the virtual offset of the first record."""
    r = bgzf.BgzfReader(data)
    hdr = bam.read_header_stream(r)
    return hdr, r.tell_voffset()


def read_header_voffset(path_or_bytes) -> Tuple[bam.BamHeader, int]:
    """Header + first-record virtual offset, pulling file bytes incrementally
    (a 100GB BAM must not be slurped to learn its reference dictionary)."""
    if not isinstance(path_or_bytes, str):
        return _read_header(path_or_bytes)
    hfs = fs.get_fs(path_or_bytes)
    size = hfs.size(path_or_bytes)
    chunk = 1 << 20
    while True:
        data = hfs.read_range(path_or_bytes, 0, chunk)
        try:
            return _read_header(data)
        except (bgzf.BgzfError, bam.BamError):
            if chunk >= size:
                raise
            chunk *= 8


def read_header(path_or_bytes) -> bam.BamHeader:
    return read_header_voffset(path_or_bytes)[0]


def read_virtual_range(
    data: bytes,
    vstart: int,
    vend: int,
    with_keys: bool = True,
    threads: Optional[int] = None,
    interval_chunks: Optional[List[Tuple[int, int]]] = None,
    fields: Optional[Sequence[str]] = None,
    device_inflate: bool = False,
    inflate_fn=None,
    errors: str = "strict",
    n_refs: Optional[int] = None,
    window_at_eof: bool = True,
    stream=None,
) -> RecordBatch:
    """Decode all records whose start voffset lies in ``[vstart, vend)``.

    The batched equivalent of BAMRecordReader's span iterator
    (BAMRecordReader.java:179-183): blocks from ``vstart>>16`` through the
    block containing ``vend`` are inflated in one native call; the record
    chain is walked from ``vstart&0xffff``; records starting at voffset ≥
    vend are cut off.  Records *spanning* past vend are completed by
    inflating spill blocks (the ``…|0xffff`` contract guarantees the next
    split will skip them via its own vstart).

    ``device_inflate`` routes the batched block inflate through the
    lockstep-lane device codec (ops.flate.inflate_blocks_device): the
    split's blocks ship to the accelerator *compressed* (≈4x fewer h2d
    bytes than the inflated stream) and members the device tier rejects
    fall back to native zlib per member — output is identical either way.

    ``inflate_fn(data, coffsets, csizes, usizes) -> (out, out_offsets)``,
    when given, replaces the main-window member inflate entirely (both
    the native and device tiers) — the serve daemon routes reads through
    its cross-request lane batcher this way.  Spill blocks (a tail record
    straddling the window) still inflate natively: they are per-request
    by construction.

    ``errors="salvage"`` (with ``n_refs`` from the header) switches to
    the quarantining reader (:func:`_read_virtual_range_salvage`): corrupt
    members are skipped with guesser re-sync instead of raising.  The
    strict path below is byte-for-byte the pre-salvage hot path — the
    policy costs one branch here.  ``window_at_eof=False`` tells the
    salvage reader its buffer is a window that stops short of the file's
    end, so trouble near the window edge raises (the caller widens)
    instead of being mistaken for corruption.
    """
    if fields is not None and with_keys:
        # Keys need refid/pos/flag + record extents even if the caller's
        # subset omits them.
        fields = tuple(
            dict.fromkeys(tuple(fields) + SORT_FIELDS)
        )
    if errors == "salvage":
        if n_refs is None:
            raise ValueError("salvage mode needs n_refs from the header")
        # Clean-input fast path: run the strict reader first and only
        # drop into the quarantining reader when it actually raises —
        # salvage mode on a clean file costs one try-frame (the bench's
        # ``salvage_overhead_pct`` pins this at ≈0).  A corruption raise
        # wastes the partial strict work; corruption is the rare case.
        try:
            return read_virtual_range(
                data,
                vstart,
                vend,
                with_keys=with_keys,
                threads=threads,
                interval_chunks=interval_chunks,
                fields=fields,
                device_inflate=device_inflate,
                inflate_fn=inflate_fn,
                stream=stream,
            )
        except (bgzf.BgzfError, bam.BamError):
            METRICS.count("salvage.strict_fallbacks", 1)
        return _read_virtual_range_salvage(
            data,
            vstart,
            vend,
            n_refs=n_refs,
            with_keys=with_keys,
            interval_chunks=interval_chunks,
            fields=fields,
            window_at_eof=window_at_eof,
        )
    if vstart >= vend:
        # Degenerate split (e.g. header larger than the first byte split:
        # BAMInputFormat.java:497-516's FIXME case) — an empty iterator in
        # the reference, an empty batch here.
        return RecordBatch(
            soa=_empty_soa(fields), data=np.empty(0, np.uint8),
            keys=np.empty(0, np.int64),
        )
    file_end = len(data)
    cstart = vstart >> 16
    cend = min(vend >> 16, file_end)

    # Blocks whose start lies in [cstart, cend]; then spill as needed.
    co_l: List[int] = []
    cs_l: List[int] = []
    us_l: List[int] = []
    pos = cstart
    while pos < file_end and pos <= cend:
        csize, usize = bgzf.read_block_at(data, pos)
        co_l.append(pos)
        cs_l.append(csize)
        us_l.append(usize)
        pos += csize
    spill_pos = pos

    dev_cell: List = [None]  # device-resident copy of the inflated window

    def inflate(co, cs, us):
        if inflate_fn is not None:
            return inflate_fn(
                data,
                np.asarray(co, dtype=np.int64),
                np.asarray(cs, dtype=np.int32),
                np.asarray(us, dtype=np.int32),
            )
        if stream is not None and device_inflate:
            # Stream client: the decode rides the DeviceStream's tier
            # seam (policy + OOM accounting + host tier-down in one
            # place — the same seam the serve lane batcher uses).
            out, offs, dev = stream.decode_members(
                data,
                co,
                cs,
                us,
                return_device=True,
                threads=threads,
                on_error="host",
            )
            dev_cell[0] = dev
            return out, offs
        if device_inflate:
            from ..ops import flate

            try:
                out, offs, dev = flate.inflate_blocks_device(
                    data,
                    np.asarray(co, dtype=np.int64),
                    np.asarray(cs, dtype=np.int32),
                    np.asarray(us, dtype=np.int32),
                    return_device=True,
                )
                dev_cell[0] = dev
                return out, offs
            except Exception as e:
                # Device tier failure is never fatal to a read — tier
                # down to the native host codec for the whole window.
                METRICS.count("bam.device_inflate_fallback", 1)
                from ..utils.backend import is_resource_exhausted

                if is_resource_exhausted(e):
                    # HBM exhaustion (not a decode bug): itemized so the
                    # OOM degradation path is auditable end to end.
                    METRICS.count("bam.oom_tierdown", 1)
        return native.inflate_blocks(
            data,
            np.asarray(co, dtype=np.int64),
            np.asarray(cs, dtype=np.int32),
            np.asarray(us, dtype=np.int32),
            threads=threads,
        )

    with span("bam.stage.inflate", category="stage"):
        out, offs = inflate(co_l, cs_l, us_l)
    # ``buf[:plen]`` is the live payload.  The no-spill fast path keeps the
    # native output zero-copy; spills grow the buffer geometrically so a
    # tail record spanning K blocks costs O(window + spill) amortized, not
    # O(K·window) (ADVICE r1: per-block whole-array concat was quadratic).
    buf = out
    plen = len(out)
    # Per-block tables, extended in place when spill blocks are pulled in.
    uoffs_l: List[int] = [int(x) for x in offs[:-1]]  # payload offsets
    voffs_l: List[int] = list(co_l)  # compressed offsets
    usize_l: List[int] = list(us_l)

    # Payload offset of vstart.
    up0 = vstart & 0xFFFF
    if up0 > (us_l[0] if us_l else 0):
        raise bgzf.BgzfError("vstart uoffset beyond block payload")

    def spill_one() -> bool:
        nonlocal spill_pos, buf, plen
        if spill_pos >= file_end:
            return False
        csize, usize = bgzf.read_block_at(data, spill_pos)
        sp_out, _ = native.inflate_blocks(
            data,
            np.asarray([spill_pos], dtype=np.int64),
            np.asarray([csize], dtype=np.int32),
            np.asarray([usize], dtype=np.int32),
        )
        if plen + usize > len(buf):
            grown = np.empty(
                max(2 * len(buf), plen + usize), dtype=np.uint8
            )
            grown[:plen] = buf[:plen]
            buf = grown
        buf[plen : plen + usize] = sp_out
        uoffs_l.append(plen)
        voffs_l.append(spill_pos)
        usize_l.append(usize)
        plen += usize
        spill_pos += csize
        return True

    # Payload-offset cutoff equivalent to "record voffset >= vend" under the
    # exact-block-end normalization rule: monotone in payload position, so
    # the voffset comparison of the per-record walk becomes one searchsorted.
    vc = vend >> 16
    if vc >= file_end or not voffs_l:
        vend_off = None  # …|0xffff last-split contract: take everything
    else:
        bi = max(0, int(np.searchsorted(voffs_l, vc, side="right")) - 1)
        if voffs_l[bi] == vc:
            vend_off = uoffs_l[bi] + min(vend & 0xFFFF, usize_l[bi])
        else:
            # vend falls inside block bi's compressed extent: every record
            # of block bi precedes it, the next block's records don't.
            vend_off = uoffs_l[bi] + usize_l[bi]

    # Walk the record chain natively from vstart; a truncated tail record
    # (spanning past the loaded window) pulls in spill blocks and resumes.
    rec_parts: List[np.ndarray] = []
    p = uoffs_l[0] + up0 if uoffs_l else 0
    with span("bam.stage.parse", category="stage"):
        while True:
            offs, resume = native.record_chain_partial(buf[:plen], p, plen)
            if vend_off is not None:
                k = int(np.searchsorted(offs, vend_off, side="left"))
            else:
                k = len(offs)
            rec_parts.append(offs[:k])
            if k < len(offs):
                break  # saw a record at/after vend: done
            if vend_off is not None and resume >= vend_off:
                break
            if resume + 4 <= plen:
                # chain stopped on a truncated body inside the window
                if not spill_one():
                    raise bam.BamError("truncated record at end of file")
            elif spill_pos < file_end:
                spill_one()
            else:
                # ≤3 trailing bytes at EOF: lenient, like the iterator
                # stopping when no full size word remains.
                break
            p = resume

        arr = buf[:plen]
        offsets = (
            np.concatenate(rec_parts)
            if rec_parts
            else np.empty(0, dtype=np.int64)
        )
        soa = (
            bam.soa_decode(arr, offsets, fields=fields)
            if len(offsets)
            else _empty_soa(fields)
        )
    if interval_chunks is not None and len(offsets):
        keep = _voffset_mask(
            offsets,
            np.asarray(uoffs_l, dtype=np.int64),
            np.asarray(voffs_l, dtype=np.int64),
            usize_l,
            interval_chunks,
        )
        soa = {k: v[keep] for k, v in soa.items()}
    with span("bam.stage.key", category="stage"):
        keys = (
            bam.soa_keys(soa, arr)
            if with_keys and len(soa["rec_off"])
            else np.empty(0, dtype=np.int64)
        )
    METRICS.count("bam.blocks_inflated", len(voffs_l))
    METRICS.count("bam.bytes_inflated", plen)
    METRICS.count("bam.records_decoded", len(offsets))
    if interval_chunks is not None:
        METRICS.count("bam.records_kept", len(soa["rec_off"]))
    # The device-resident copy is only exact on the no-spill fast path
    # (spill blocks are host-inflated into a grown buffer the device
    # never saw).  Exact: the batch takes ledger ownership of the HBM
    # window; inexact: give it straight back so the codec's registration
    # doesn't read as a leak.
    device_data = None
    if dev_cell[0] is not None:
        if plen == len(out):
            device_data = (
                stream.attach_window(dev_cell[0])
                if stream is not None
                else LEDGER.transfer(dev_cell[0], "bam.split_window")
            )
        else:
            LEDGER.release(dev_cell[0])
    return RecordBatch(
        soa=soa, data=arr, keys=keys, device_data=device_data
    )


def _read_virtual_range_salvage(
    data: bytes,
    vstart: int,
    vend: int,
    n_refs: int,
    with_keys: bool = True,
    interval_chunks: Optional[List[Tuple[int, int]]] = None,
    fields: Optional[Sequence[str]] = None,
    window_at_eof: bool = True,
) -> RecordBatch:
    """The quarantining split reader: survive corrupt members and torn
    record chains, return every record that is provably intact.

    Reference stance: the library's whole point is making sense of BGZF
    at arbitrary byte offsets (split guessers, per-record sanity rules),
    yet the strict readers throw away that machinery the moment a byte is
    wrong mid-job.  This reader turns it back on:

    1. **Member scan with re-sync** — walk block headers from the split's
       start; an unparseable header (bit-flipped magic, lying BSIZE)
       quarantines bytes up to the next plausible header
       (:func:`spec.bgzf.find_next_block`, the guesser's phase-1 scan).
    2. **Per-member inflate** — each member decodes under the CRC32/ISIZE
       gates; a failing member is quarantined (the strict batch inflate
       would have aborted the job).
    3. **Segmented chain walk** — file-contiguous runs of good members
       form segments; the record chain cannot cross a quarantined gap, so
       each segment after the first re-syncs its first record with the
       guesser's record sanity rules + strict trial decode
       (:func:`io.guesser.find_record_start_in_payload`).  Records
       truncated by a gap (or failing mid-segment sanity) are dropped and
       the walk re-syncs past them.
    4. **Spill continuation** — a tail record straddling the split end
       still completes through following members, as in strict mode.

    Accounting (all under ``salvage.*`` in METRICS): quarantined members
    and bytes (counted once per file region — events at/after this
    split's end block are left to the next split), re-syncs and failures,
    dropped records, and the surviving record count.  Device tiers and
    the lane batcher are deliberately bypassed — salvage is the degraded
    host-correctness path.
    """
    if vstart >= vend:
        return RecordBatch(
            soa=_empty_soa(fields), data=np.empty(0, np.uint8),
            keys=np.empty(0, np.int64),
        )
    file_end = len(data)
    cstart = vstart >> 16
    cend = min(vend >> 16, file_end)
    last_split = (vend >> 16) >= file_end

    def _count_quarantine(co: int, nbytes: int) -> None:
        # A member at/after the end block belongs to the next split's
        # window — counting it here too would double-report.
        if co < cend or last_split:
            METRICS.count("salvage.members_quarantined", 1)
            METRICS.count("salvage.bytes_quarantined", nbytes)

    def _widen_guard(pos: int) -> None:
        # Trouble within one max-block-size of a window edge that is NOT
        # the file's end is indistinguishable from window truncation:
        # raise so read_split widens the margin and retries.
        if not window_at_eof and pos + bgzf.MAX_BLOCK_SIZE > file_end:
            raise bgzf.BgzfError(
                f"salvage: window too small to classify bytes at {pos}"
            )

    # ---- 1+2: member scan with re-sync, per-member inflate -------------
    good_co: List[int] = []
    good_cs: List[int] = []
    good_us: List[int] = []
    payloads: List[bytes] = []
    pos = cstart
    while pos < file_end and pos <= cend:
        try:
            csize, usize = bgzf.read_block_at(data, pos)
        except bgzf.BgzfError:
            _widen_guard(pos)
            nxt = bgzf.find_next_block(data, pos + 1)
            npos = nxt[0] if nxt is not None else file_end
            if nxt is None:
                _widen_guard(npos)
            _count_quarantine(pos, npos - pos)
            pos = npos
            continue
        try:
            payload, _ = bgzf.inflate_block(data, pos)
        except bgzf.BgzfError:
            _count_quarantine(pos, csize)
            pos += csize
            continue
        good_co.append(pos)
        good_cs.append(csize)
        good_us.append(len(payload))
        payloads.append(payload)
        pos += csize
    spill_pos = pos

    buf = bytearray()
    uoffs: List[int] = []
    for p_ in payloads:
        uoffs.append(len(buf))
        buf.extend(p_)

    # ---- segment boundaries (contiguity breaks at every quarantine) ----
    seg_starts: List[int] = []  # indices into the good-member tables
    for k in range(len(good_co)):
        if k == 0 or good_co[k] != good_co[k - 1] + good_cs[k - 1]:
            seg_starts.append(k)
    seg_bounds: List[Tuple[int, int]] = [
        (s, seg_starts[i + 1] if i + 1 < len(seg_starts) else len(good_co))
        for i, s in enumerate(seg_starts)
    ]

    # ---- vend cutoff over the good-member tables (monotone, as strict) -
    vc = vend >> 16
    if vc >= file_end or not good_co:
        vend_off: Optional[int] = None
    elif vc < good_co[0]:
        vend_off = 0
    else:
        bi = max(0, int(np.searchsorted(good_co, vc, side="right")) - 1)
        if good_co[bi] == vc:
            vend_off = uoffs[bi] + min(vend & 0xFFFF, good_us[bi])
        else:
            vend_off = uoffs[bi] + good_us[bi]

    from .guesser import find_record_start_in_payload

    rec_parts: List[np.ndarray] = []
    up0 = vstart & 0xFFFF
    done = False

    def spill_one() -> bool:
        """Extend the frontier segment by one member (salvage rules: a
        corrupt spill member just ends the chain — the dropped tail
        record is counted by the caller, the member by the next split)."""
        nonlocal spill_pos
        if spill_pos >= file_end:
            if not window_at_eof:
                # The tail record continues past the window, not past the
                # file: widen, don't drop.
                raise bgzf.BgzfError(
                    "salvage: window too small for spilled tail record"
                )
            return False
        try:
            csize, usize = bgzf.read_block_at(data, spill_pos)
            payload, _ = bgzf.inflate_block(data, spill_pos)
        except bgzf.BgzfError:
            _widen_guard(spill_pos)
            return False
        good_co.append(spill_pos)
        good_cs.append(csize)
        good_us.append(len(payload))
        uoffs.append(len(buf))
        buf.extend(payload)
        spill_pos += csize
        return True

    for si, (k0, k1) in enumerate(seg_bounds):
        if done:
            break
        seg_u0 = uoffs[k0]
        seg_u1 = uoffs[k1 - 1] + good_us[k1 - 1]
        if vend_off is not None and seg_u0 >= vend_off:
            break
        # Frontier segment: the last one, ending exactly at the scan
        # cursor — the only segment a spill block can legally extend.
        at_frontier = (
            si == len(seg_bounds) - 1
            and good_co[k1 - 1] + good_cs[k1 - 1] == spill_pos
        )
        # Starting point: the split's own vstart is a planned record
        # boundary IF its block survived; any other segment re-syncs.
        if si == 0 and k0 == 0 and good_co[0] == cstart and up0 <= good_us[0]:
            p = seg_u0 + up0
        else:
            METRICS.count("salvage.resyncs", 1)
            r = find_record_start_in_payload(
                np.frombuffer(bytes(buf[seg_u0:seg_u1]), np.uint8), n_refs
            )
            if r is None:
                METRICS.count("salvage.resync_failed", 1)
                continue
            p = seg_u0 + r
        guard = 0
        while p < seg_u1 and guard < 1000:
            guard += 1
            # A mutable bytearray exposes a zero-copy uint8 view; the
            # view is rebuilt per iteration because spill_one() may have
            # grown (and reallocated) the buffer.
            arr_now = np.frombuffer(
                memoryview(buf), dtype=np.uint8, count=seg_u1
            )
            offs, resume = native.record_chain_partial(
                arr_now, p, seg_u1
            )
            if vend_off is not None:
                k = int(np.searchsorted(offs, vend_off, side="left"))
            else:
                k = len(offs)
            rec_parts.append(np.asarray(offs[:k], dtype=np.int64))
            if k < len(offs) or (
                vend_off is not None and resume >= vend_off
            ):
                done = True
                break
            if resume + 4 > seg_u1 and not at_frontier:
                break  # ≤3 trailing bytes at a gap: lenient, as strict EOF
            if at_frontier:
                if resume + 4 > seg_u1 and spill_pos >= file_end:
                    break  # ≤3 trailing bytes at file EOF
                if spill_one():
                    seg_u1 = uoffs[-1] + good_us[-1]
                    p = resume
                    continue
                if resume < seg_u1:
                    # Torn tail record at the end of the salvageable data.
                    METRICS.count("salvage.records_dropped", 1)
                break
            # A record truncated by the following gap, or an unparseable
            # record mid-segment: drop it and re-sync past its start.
            METRICS.count("salvage.records_dropped", 1)
            METRICS.count("salvage.resyncs", 1)
            r = find_record_start_in_payload(
                np.frombuffer(bytes(buf[seg_u0:seg_u1]), np.uint8),
                n_refs,
                start=resume - seg_u0 + 1,
            )
            if r is None:
                METRICS.count("salvage.resync_failed", 1)
                break
            p = seg_u0 + r

    arr = np.frombuffer(bytes(buf), dtype=np.uint8)
    offsets = (
        np.concatenate(rec_parts)
        if rec_parts
        else np.empty(0, dtype=np.int64)
    )
    soa = (
        bam.soa_decode(arr, offsets, fields=fields)
        if len(offsets)
        else _empty_soa(fields)
    )
    if interval_chunks is not None and len(offsets):
        keep = _voffset_mask(
            offsets,
            np.asarray(uoffs, dtype=np.int64),
            np.asarray(good_co, dtype=np.int64),
            good_us,
            interval_chunks,
        )
        soa = {k: v[keep] for k, v in soa.items()}
    keys = (
        bam.soa_keys(soa, arr)
        if with_keys and len(soa["rec_off"])
        else np.empty(0, dtype=np.int64)
    )
    METRICS.count("bam.blocks_inflated", len(good_co))
    METRICS.count("bam.bytes_inflated", len(arr))
    METRICS.count("bam.records_decoded", len(offsets))
    METRICS.count("salvage.records_salvaged", len(offsets))
    if interval_chunks is not None:
        METRICS.count("bam.records_kept", len(soa["rec_off"]))
    return RecordBatch(soa=soa, data=arr, keys=keys)


def _voffset_mask(offsets, block_uoffs, block_voffs, us_l, chunks):
    """Mask of records whose start voffset falls inside any interval chunk
    (device-side overlap filtering happens later; this is the coarse
    chunk-span cut the reference reader does via createIndexIterator)."""
    bi = np.searchsorted(block_uoffs, offsets, side="right") - 1
    in_block = offsets - block_uoffs[bi]
    # normalize exact-end offsets onto the next block
    us = np.asarray(us_l, dtype=np.int64)
    over = (bi + 1 < len(us)) & (in_block >= us[np.minimum(bi, len(us) - 1)])
    bi = np.where(over, bi + 1, bi)
    in_block = offsets - block_uoffs[bi]
    voffs = (block_voffs[bi] << 16) | in_block
    keep = np.zeros(len(offsets), dtype=bool)
    for beg, end in chunks:
        keep |= (voffs >= beg) & (voffs < end)
    return keep


def _empty_soa(fields: Optional[Sequence[str]] = None) -> dict:
    return {
        k: np.empty(0, dtype=np.int64)
        for k in (bam.SOA_FIELDS if fields is None else fields)
    }


def gather_record_array(
    batch, order: Optional[np.ndarray] = None
) -> np.ndarray:
    """Concatenate (block_size word + body) of every record, permuted by
    ``order`` — one native memcpy per record; the write-side analog of the
    SoA decode.  Accepts a :class:`RecordBatch` (one contiguous payload) or
    a :class:`ChunkedRecords` (per-split payloads, gathered in place; the
    O(n) extent validation runs on the first gather only)."""
    soa = batch.soa
    if len(soa["rec_off"]) == 0:
        return np.empty(0, np.uint8)
    if isinstance(batch, ChunkedRecords):
        out = native.gather_records_chunked(
            batch.chunks, batch.chunk_id, soa["rec_off"], soa["rec_len"],
            order, check=not batch._validated,
        )
        batch._validated = True
        return out
    return native.gather_records(
        batch.data, soa["rec_off"], soa["rec_len"], order
    )


def gather_record_bytes(
    batch, order: Optional[np.ndarray] = None
) -> bytes:
    return gather_record_array(batch, order).tobytes()


def patch_flags(
    stream: np.ndarray, rec_starts: np.ndarray, bits: int = bam.FLAG_DUPLICATE
) -> None:
    """OR ``bits`` into the flag field of the records whose size words sit
    at ``rec_starts`` in a gathered record stream (in place).

    The flag is the little-endian u16 at body offset 14, i.e. bytes 18-19
    past each record's block_size word.  This is the dedup write path: the
    sorted gather output — never the source batch payload — is patched,
    so the LazyBAMRecord stance (the sort pipeline does not mutate record
    bytes it read) is preserved.
    """
    if len(rec_starts) == 0:
        return
    stream[rec_starts + 18] |= np.uint8(bits & 0xFF)
    stream[rec_starts + 19] |= np.uint8((bits >> 8) & 0xFF)


def _ragged_copy(
    dst: np.ndarray,
    dst_off: np.ndarray,
    src: np.ndarray,
    src_off: np.ndarray,
    lens: np.ndarray,
) -> None:
    """``dst[dst_off[i] : +lens[i]] = src[src_off[i] : +lens[i]]`` for
    every i, as one fancy-index pass (no per-record Python loop)."""
    lens = lens.astype(np.int64)
    total = int(lens.sum())
    if total == 0:
        return
    base = np.cumsum(lens) - lens
    within = np.arange(total, dtype=np.int64) - np.repeat(base, lens)
    dst[np.repeat(dst_off.astype(np.int64), lens) + within] = src[
        np.repeat(src_off.astype(np.int64), lens) + within
    ]


def rebuild_record_stream(
    data: np.ndarray,
    rec_off: np.ndarray,
    rec_len: np.ndarray,
    cut_off: np.ndarray,
    cut_len: np.ndarray,
    append_blob: np.ndarray,
    append_off: np.ndarray,
    append_len: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Re-emit records with a per-record tag splice and append — the
    write-side machinery under the fixmate MC-tag patch.

    Each output record is ``u32 size word + body[:cut_off] +
    body[cut_off+cut_len:] + append_blob[append_off : +append_len]``
    with the size word updated to the new body length.  A record with
    ``cut_len == 0`` and ``append_len == 0`` round-trips byte-for-byte
    (set ``cut_off = rec_len``).  Everything is vectorized ragged
    copies; the source payload is never mutated (the ``patch_flags``
    stance — the sort/collate pipelines rewrite only gathered output).

    Returns ``(stream, new_rec_off, new_rec_len)`` — new body offsets
    and lengths in the fresh stream, ready to wrap as a RecordBatch.
    """
    rec_off = rec_off.astype(np.int64)
    rec_len = rec_len.astype(np.int64)
    cut_off = cut_off.astype(np.int64)
    cut_len = cut_len.astype(np.int64)
    append_len = append_len.astype(np.int64)
    new_len = rec_len - cut_len + append_len
    full = 4 + new_len
    starts = np.cumsum(full) - full
    out = np.empty(int(full.sum()), dtype=np.uint8)
    for b in range(4):  # little-endian u32 size words
        out[starts + b] = ((new_len >> (8 * b)) & 0xFF).astype(np.uint8)
    _ragged_copy(out, starts + 4, data, rec_off, cut_off)
    _ragged_copy(
        out,
        starts + 4 + cut_off,
        data,
        rec_off + cut_off + cut_len,
        rec_len - cut_off - cut_len,
    )
    _ragged_copy(
        out,
        starts + 4 + rec_len - cut_len,
        append_blob,
        append_off,
        append_len,
    )
    return out, starts + 4, new_len


def _write_part_device(
    batch,
    order: Optional[np.ndarray],
    dup_mask: Optional[np.ndarray],
    level: int,
    conf: Optional[Configuration],
    stream=None,
) -> Optional[bytes]:
    """The device-resident part assembly, now owned by the DeviceStream
    (:meth:`~hadoop_bam_tpu.device_stream.DeviceStream.encode_part`):
    sorted gather + markdup flag patch + per-member CRC32 on chip,
    deflate lanes fed device-to-device with the gathered column donated
    into the CRC launch — the only d2h traffic is the compressed part
    blob (+ CRC column).  This wrapper keeps the write path's historic
    seam: callers without a stream get an ephemeral one (the gates
    resolve from env/conf/cached-RTT, so construction is cheap), and
    every tier-down reason (``bam.device_write_tierdown.*`` /
    ``bam.device_write_fallback``) is recorded exactly as before —
    LEDGER registration of the gather column included."""
    if stream is None:
        from ..device_stream import DeviceStream

        stream = DeviceStream(conf=conf)
    return stream.encode_part(batch, order=order, dup_mask=dup_mask,
                              level=level)


def write_part_fast(
    stream,
    batch: "RecordBatch",
    order: Optional[np.ndarray] = None,
    level: int = 6,
    splitting_bai_stream=None,
    granularity: int = indices.DEFAULT_GRANULARITY,
    threads: Optional[int] = None,
    device_deflate: Optional[bool] = None,
    conf: Optional[Configuration] = None,
    dup_mask: Optional[np.ndarray] = None,
    device_write: Optional[bool] = None,
    device_stream=None,
) -> int:
    """Write a headerless, terminator-less part from a batch in one shot:
    vectorized record gather + batched deflate.  Per-record virtual
    offsets for the inline `.splitting-bai` are reconstructed analytically
    from the deterministic blocking (payload cut every ``block_payload``
    bytes), so no per-record Python loop runs.  Returns bytes written.

    ``device_write`` selects the fully device-resident assembly
    (:func:`_write_part_device`): when the batch carries HBM residency
    (``RecordBatch.device_data`` / ``ChunkedRecords.device_flat``), the
    sorted gather, the markdup flag patch, the per-member CRC32 and the
    LZ77+Huffman emit all run on chip and the host only frames the
    compressed bytes — no uncompressed-stream upload at all.  Default:
    the ``hadoopbam.write.device`` conf key / ``HBAM_DEVICE_WRITE`` env /
    local-latency auto rule (``ops.flate.device_write_enabled``).  Output
    is byte-identical to the host gather + lanes-deflate path; any
    tier-down (missing residency, int32 domain, device failure) falls
    through to that path with its reason counted.

    ``device_deflate`` routes the (host-gathered) deflate through the
    lockstep-lane Pallas encoder (``ops.flate.deflate_blocks_device``):
    the host gathers the permuted records and does gzip framing + CRC32,
    the LZ77 match-find and Huffman emit run on chip.  Default: the
    ``hadoopbam.deflate.lanes`` conf key / ``HBAM_DEFLATE_LANES`` env /
    local-latency auto rule (``ops.flate.deflate_lanes_tier_enabled``).
    A device failure falls back to the threaded native zlib tier for the
    whole part.

    ``dup_mask`` (bool per *batch row*, same index space as
    ``soa['rec_off']``) marks rows whose written copy gets
    ``FLAG_DUPLICATE`` ORed in via :func:`patch_flags` — the dedup
    subsystem's flag-rewrite stage, applied to the gathered stream just
    before deflate."""
    if device_write is None:
        if device_stream is not None:
            device_write = device_stream.policy.device_write
        else:
            from ..ops.flate import device_write_enabled

            device_write = device_write_enabled(conf)
    blob = None
    block_payload = bgzf.MAX_PAYLOAD
    if device_write:
        from ..ops import flate as _flate

        blob = _write_part_device(
            batch, order, dup_mask, level, conf, stream=device_stream
        )
        if blob is not None:
            block_payload = _flate.DEV_LZ_PAYLOAD
    if blob is None:
        with span("bam.stage.gather", category="stage"):
            payload = gather_record_array(batch, order)
        if dup_mask is not None:
            dm = dup_mask[order] if order is not None else dup_mask
            if dm.any():
                ln = batch.soa["rec_len"].astype(np.int64) + 4
                if order is not None:
                    ln = ln[order]
                starts = np.cumsum(ln) - ln
                patch_flags(payload, starts[dm])
                METRICS.count(
                    "bam.duplicate_flags_patched", int(dm.sum())
                )
        if device_deflate is None:
            if device_stream is not None:
                device_deflate = device_stream.policy.deflate_lanes
            else:
                from ..ops.flate import deflate_lanes_tier_enabled

                device_deflate = deflate_lanes_tier_enabled(conf)
        # Explicit block size: the analytic voffset math below depends
        # on it.
        if device_deflate:
            from ..ops import flate as _flate

            try:
                blob = _flate.deflate_blocks_device(
                    payload,
                    level=level,
                    block_payload=_flate.DEV_LZ_PAYLOAD,
                    use_lanes=True,
                )
                block_payload = _flate.DEV_LZ_PAYLOAD
            except Exception:
                METRICS.count("bam.device_deflate_fallback", 1)
                blob = None
                block_payload = bgzf.MAX_PAYLOAD
        if blob is None:
            with span("bam.stage.deflate", category="stage"):
                blob = native.deflate_blocks(
                    payload, level=level, threads=threads,
                    block_payload=block_payload,
                )
    with span("bam.stage.write", category="stage"):
        stream.write(blob)
    if splitting_bai_stream is not None:
        ln = batch.soa["rec_len"].astype(np.int64) + 4
        if order is not None:
            ln = ln[order]
        logical = np.cumsum(ln) - ln  # stream offset of each record
        co, _, _ = native.scan_blocks(blob)
        bi = logical // block_payload
        voffs = (co[bi] << 16) | (logical % block_payload)
        b = indices.SplittingBaiBuilder(granularity)
        n = len(voffs)
        pick = np.zeros(n, dtype=bool)
        if n:
            pick[0] = True
            pick |= (np.arange(n) + 1) % granularity == 0
        b.voffsets = [int(v) for v in voffs[pick]]
        b.count = n
        b.finish(len(blob)).save(splitting_bai_stream)
    return len(blob)


# ---------------------------------------------------------------------------
# Writer (BAMRecordWriter.java semantics)
# ---------------------------------------------------------------------------


class BamOutputWriter:
    """BGZF BAM writer with optional header, terminator-less part mode, and
    inline `.splitting-bai` construction (BAMRecordWriter.java:69-89,131-167).
    """

    def __init__(
        self,
        stream,
        header: bam.BamHeader,
        write_header: bool = True,
        append_terminator: bool = True,
        write_splitting_bai: bool = False,
        splitting_bai_stream=None,
        granularity: int = indices.DEFAULT_GRANULARITY,
        level: int = 6,
    ):
        self._w = bgzf.BgzfWriter(
            stream, level=level, append_terminator=append_terminator
        )
        self.header = header
        self._sb = (
            indices.SplittingBaiBuilder(granularity)
            if write_splitting_bai
            else None
        )
        self._sb_stream = splitting_bai_stream
        self._bytes_out = 0
        self._stream = stream
        if write_header:
            self._w.write(header.encode())

    def write_record(self, rec: bam.BamRecord) -> None:
        self.write_raw(rec.raw)

    def write_raw(self, body: bytes) -> None:
        if self._sb is not None:
            self._sb.process_alignment(self._w.tell_voffset())
        self._w.write(struct.pack("<I", len(body)) + body)

    def write_batch(self, batch: RecordBatch, order: Optional[np.ndarray] = None) -> None:
        """Write records of a batch (optionally permuted), without
        materializing record objects."""
        idx = range(batch.n_records) if order is None else order
        for i in idx:
            off = int(batch.soa["rec_off"][i])
            ln = int(batch.soa["rec_len"][i])
            self.write_raw(batch.data[off : off + ln].tobytes())

    def close(self, file_size_for_index: Optional[int] = None) -> None:
        self._w.close()
        if self._sb is not None and self._sb_stream is not None:
            size = (
                file_size_for_index
                if file_size_for_index is not None
                else self._stream.tell()
            )
            self._sb.finish(size).save(self._sb_stream)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
