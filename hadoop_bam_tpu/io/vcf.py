"""VCF input/output: dispatch, splittable planning, batched reading, merge.

Reference parity:
- format dispatch by extension then content sniff — gunzip if needed, first
  byte 'B' (BCF magic) vs '#' (VCFFormat.java:57-72; trust-exts via
  ``hadoopbam.vcf.trust-exts``),
- splittability: plain text → byte splits; ``.gz``/``.bgz`` only when really
  BGZF (VCFInputFormat.java:198-224); plain gzip is one unsplittable split,
- tabix-index interval filtering of splits (VCFInputFormat.java:387-471) and
  per-record overlap filtering (VCFRecordReader.java:196-217),
- validation stringency STRICT/LENIENT/SILENT
  (``hadoopbam.vcfrecordreader.validation-stringency``,
  VCFRecordReader.java:80-92,180-194),
- writer with swallowed-header part mode (VCFRecordWriter.java:152-177) and
  the part merger incl. the BCF-unsupported guard
  (util/VCFFileMerger.java:44-134),
- VCFHeaderReader: try-VCF-then-BCF header sniffing
  (util/VCFHeaderReader.java:51-78).
"""

from __future__ import annotations

import gzip
import os
from typing import List, Optional, Tuple

import numpy as np

from ..conf import (
    Configuration,
    VCF_INTERVALS,
    VCF_TRUST_EXTS,
    VCFRECORDREADER_VALIDATION_STRINGENCY,
)
from ..spec import bgzf, indices
from . import fs
from ..spec.vcf import (
    FormatException,
    VariantContext,
    VcfHeader,
    parse_variant_line,
    variant_key,
)
from ..utils import nio
from ..utils.intervals import Interval, parse_intervals
from .splits import ByteSplit
from .text import SplitLineReader


def sniff_vcf_format(path: str, trust_exts: bool = True) -> Optional[str]:
    """'vcf' | 'bcf' | None (VCFFormat.java:38-72 semantics)."""
    if trust_exts:
        if path.endswith(".vcf") or path.endswith(".vcf.gz") or path.endswith(".vcf.bgz") or path.endswith(".vcf.bgzf.gz"):
            return "vcf"
        if path.endswith(".bcf"):
            return "bcf"
    head = fs.get_fs(path).read_range(path, 0, 1 << 16)
    if head[:2] == b"\x1f\x8b":
        try:
            head = (
                bgzf.inflate_block(head, 0)[0]
                if bgzf.is_bgzf(head)
                else gzip.decompress(head)
            )
        except Exception:
            return None
    if head[:1] == b"B" and head[:3] == b"BCF":
        return "bcf"
    if head[:1] == b"#":
        return "vcf"
    return None


class VariantBatch:
    """Decoded split: int64 key/pos/end SoA columns for device use, with
    the per-row ``VariantContext`` objects materialized lazily — the sort
    and interval paths touch only the columns, so the per-line Python
    parse never runs for them (the LazyBAMRecord stance applied to VCF)."""

    def __init__(
        self,
        header: VcfHeader,
        variants: Optional[List[VariantContext]] = None,
        keys: Optional[np.ndarray] = None,
        pos: Optional[np.ndarray] = None,
        end: Optional[np.ndarray] = None,
        materializer=None,
    ):
        self.header = header
        self.keys = keys if keys is not None else np.empty(0, np.int64)
        self.pos = pos if pos is not None else np.empty(0, np.int64)
        self.end = end if end is not None else np.empty(0, np.int64)
        self._variants = variants
        self._materializer = materializer

    @property
    def variants(self) -> List[VariantContext]:
        if self._variants is None:
            self._variants = (
                self._materializer() if self._materializer else []
            )
        return self._variants

    @property
    def n_records(self) -> int:
        return len(self.keys)


class VcfInputFormat:
    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()

    # -- stringency (VCFRecordReader.java:80-92) ----------------------------

    def _stringency(self) -> str:
        s = (
            self.conf.get(VCFRECORDREADER_VALIDATION_STRINGENCY, "STRICT")
            or "STRICT"
        ).upper()
        if s not in ("STRICT", "LENIENT", "SILENT"):
            raise ValueError(f"invalid validation stringency {s}")
        return s

    def _intervals(self) -> Optional[List[Interval]]:
        return parse_intervals(self.conf.get(VCF_INTERVALS))

    # -- planning -----------------------------------------------------------

    def get_splits(self, paths, split_size: int = 4 << 20):
        """Partition by sniffed format and delegate BCF files to the BCF
        planner (VCFInputFormat.java:271-297); returns a mixed list of
        ByteSplit (VCF) and FileVirtualSplit (BCF)."""
        trust = self.conf.get_boolean(VCF_TRUST_EXTS, True)
        bcf_paths = [p for p in paths if sniff_vcf_format(p, trust) == "bcf"]
        if bcf_paths:
            from .bcf import BcfInputFormat

            sub = BcfInputFormat(self.conf)
            rest = [p for p in paths if p not in bcf_paths]
            mixed = list(sub.get_splits(bcf_paths, split_size))
            if rest:
                mixed += self.get_splits(rest, split_size)
            return mixed
        out: List[ByteSplit] = []
        for path in sorted(paths):
            pfs = fs.get_fs(path)
            size = pfs.size(path)
            head = pfs.read_range(path, 0, 18)
            if head[:2] == b"\x1f\x8b":
                if bgzf.parse_block_header(head + b"\x00" * 64, 0) or bgzf.is_bgzf(
                    pfs.read_range(path, 0, 1 << 16)
                ):
                    # BGZF: splittable on compressed offsets, snapped to
                    # block boundaries at read time.
                    out.extend(
                        ByteSplit(path, s, min(split_size, size - s))
                        for s in range(0, size, split_size)
                    )
                else:
                    # plain gzip: unsplittable (VCFInputFormat.java:216-221)
                    out.append(ByteSplit(path, 0, size))
            else:
                out.extend(
                    ByteSplit(
                        path, s, min(split_size, size - s), compressed=False
                    )
                    for s in range(0, size, split_size)
                )
        ivs = self._intervals()
        if ivs is not None:
            out = self.filter_by_interval(out, ivs)
        return out

    def filter_by_interval(
        self, splits: List[ByteSplit], intervals: List[Interval]
    ) -> List[ByteSplit]:
        """Drop splits whose tabix chunk spans miss every interval
        (VCFInputFormat.java:387-471).  Files without a .tbi are kept whole
        (warn-and-keep in the reference)."""
        out: List[ByteSplit] = []
        for s in splits:
            tbi_path = s.path + ".tbi"
            if not os.path.exists(tbi_path):
                out.append(s)
                continue
            tbi = indices.Tabix.load(tbi_path)
            keep = False
            for iv in intervals:
                for c in tbi.query(iv.contig, iv.start - 1, iv.end):
                    c_beg, c_end = c.beg >> 16, c.end >> 16
                    if c_beg < s.end and c_end >= s.start:
                        keep = True
                        break
                if keep:
                    break
            if keep:
                out.append(s)
        return out

    # -- reading ------------------------------------------------------------

    def read_split(
        self, split, data: Optional[bytes] = None
    ) -> VariantBatch:
        """Decode every variant whose line starts inside the split.  BCF
        splits (FileVirtualSplit) route to the BCF reader."""
        from .splits import FileVirtualSplit

        if isinstance(split, FileVirtualSplit):
            from .bcf import BcfInputFormat

            return BcfInputFormat(self.conf).read_split(split, data)
        header_text, payload, lo, hi = self._split_payload(split, data)
        header = VcfHeader.parse(header_text)
        stringency = self._stringency()
        intervals = self._intervals()
        fast = _read_vectorized(header, payload, lo, hi, intervals)
        if fast is not None:
            return fast
        reader = SplitLineReader(payload, lo, hi)
        variants: List[VariantContext] = []
        for _, line in reader.lines():
            if not line or line.startswith(b"#"):
                continue
            try:
                v = parse_variant_line(line.decode())
            except FormatException:
                if stringency == "STRICT":
                    raise
                continue  # LENIENT/SILENT skip (:180-194)
            if intervals is not None and not any(
                iv.overlaps(v.chrom, v.start, v.end) for iv in intervals
            ):
                continue
            variants.append(v)
        keys = np.array(
            [variant_key(header, v) for v in variants], dtype=np.int64
        )
        pos = np.array([v.pos for v in variants], dtype=np.int64)
        end = np.array([v.end for v in variants], dtype=np.int64)
        return VariantBatch(
            header=header, variants=variants, keys=keys, pos=pos, end=end
        )

    def _split_payload(
        self, split: ByteSplit, data: Optional[bytes]
    ) -> Tuple[str, bytes, int, int]:
        """(header_text, text_payload, line_scan_start, line_scan_end).

        Without a preloaded buffer the read is split-local: plain text
        reads only the split's window (+ margins), BGZF reads a bounded
        raw window and inflates just the blocks overlapping the split
        (guesser-anchored chain — the BGZFCodec+BGZFSplitGuesser path).
        Plain gzip is unsplittable and falls back to the whole payload.
        """
        if data is None:
            f = fs.get_fs(split.path)
            # Same classification get_splits used (a BGZF BC subfield may
            # sit beyond byte 18 when other extra fields precede it, so an
            # 18-byte sniff under-detects BGZF and would misroute a
            # splittable file to the whole-gzip path).
            head = f.read_range(split.path, 0, 1 << 16)
            is_bgzf_file = head[:2] == b"\x1f\x8b" and (
                bgzf.parse_block_header(head, 0) is not None
                or bgzf.is_bgzf(head)
            )
            if is_bgzf_file:
                return self._bgzf_split_payload(split, f)
            if head[:2] == b"\x1f\x8b":
                data = f.read_all(split.path)  # plain gzip: whole file
            else:
                from .text import read_split_window

                window, rsplit = read_split_window(split)
                return (
                    _header_prefix_text(split.path),
                    window,
                    rsplit.start,
                    rsplit.end,
                )
        if data[:2] == b"\x1f\x8b" and not bgzf.is_bgzf(data):
            payload = gzip.decompress(data)
            return _header_text(payload), payload, split.start, len(payload)
        if bgzf.is_bgzf(data):
            # Snap [start, end) to BGZF blocks (the BGZFCodec+guesser path,
            # util/BGZFCodec.java:56-63).  The previous block is inflated too
            # so the standard skip-partial-first-line protocol sees whether
            # local offset 0 really starts a line; one extra trailing block
            # completes the last straddling line.
            import bisect

            htext = _bgzf_header_text(data)
            blocks = bgzf.scan_blocks(data)
            starts = [b.coffset for b in blocks]
            i0 = bisect.bisect_left(starts, split.start)
            i1 = bisect.bisect_left(starts, split.end)
            if i0 >= i1:
                return htext, b"", 0, 0  # no block starts inside this split

            def inflate(i: int) -> bytes:
                return bgzf.inflate_block(data, blocks[i].coffset)[0]

            prev = inflate(i0 - 1) if i0 > 0 else b""
            mine = b"".join(inflate(i) for i in range(i0, i1))
            extra = inflate(i1) if i1 < len(blocks) else b""
            chunk = prev + mine + extra
            return htext, chunk, len(prev), len(prev) + len(mine)
        return _header_text(data), data, split.start, split.end

    def _bgzf_split_payload(
        self, split: ByteSplit, f
    ) -> Tuple[str, bytes, int, int]:
        """Split-local BGZF VCF: inflate only the blocks overlapping the
        split, located by walking the block chain from a CRC-verified
        guessed boundary inside a bounded raw window (blocks are ≤64KiB,
        so a 2·64KiB back-margin always contains a block start; the
        forward margin covers the one-extra-block line-completion rule)."""
        from .guesser import guess_bgzf_block_start

        size = f.size(split.path)
        end = min(split.end, size)
        w0 = max(0, split.start - 2 * 0xFFFF)
        w1 = min(size, end + 4 * 0xFFFF)
        window = f.read_range(split.path, w0, w1 - w0)
        # Growing prefix reads until the inflated header is complete — a
        # *terminated* #CHROM line (an unterminated fragment would silently
        # drop trailing sample columns on large cohorts) — O(header) bytes.
        n = 1 << 20
        while True:
            prefix = (
                window if w0 == 0 and size <= len(window)
                else f.read_range(split.path, 0, min(n, size))
            )
            chunk = _bgzf_header_chunk(prefix)
            i = chunk.find(b"\n#CHROM")
            if (i >= 0 and chunk.find(b"\n", i + 1) >= 0) or n >= size:
                htext = _header_text(bytes(chunk))
                break
            n *= 4
        # Walk the chain from the first verified boundary in the window.
        at = 0 if w0 == 0 else guess_bgzf_block_start(window, 0, len(window))
        if at is None or w0 + at >= end:
            return htext, b"", 0, 0
        prev = b""
        mine: List[bytes] = []
        extra = b""
        pos = at
        while pos < len(window):
            try:
                payload, csize = bgzf.inflate_block(window, pos)
            except bgzf.BgzfError:
                break  # window truncated mid-block: chain is complete
            abs_off = w0 + pos
            if abs_off < split.start:
                prev = payload  # only the last pre-split block is kept
            elif abs_off < end:
                mine.append(payload)
            else:
                extra = payload  # one block past the split end
                break
            pos += csize
        if not mine:
            return htext, b"", 0, 0
        body = b"".join(mine)
        chunk = prev + body + extra
        return htext, chunk, len(prev), len(prev) + len(body)


# Byte classes for the vectorized structural validation (exactly the
# conditions parse_variant_line raises on; anything murkier bails to the
# per-line path so error semantics — STRICT raise / LENIENT skip — stay
# bit-identical).
_ALT_OK = np.zeros(256, dtype=bool)
for _c in b"ACGTNacgtn*.0123456789_=-,":
    _ALT_OK[_c] = True
# Symbolic-allele / breakend markers: fields containing these fall back to
# the exact per-token parser (token-level validation doesn't vectorize).
_ALT_SYM = np.zeros(256, dtype=bool)
for _c in b"<>[]:":
    _ALT_SYM[_c] = True
_QUAL_OK = np.zeros(256, dtype=bool)
for _c in b"0123456789.":
    _QUAL_OK[_c] = True
del _c


def _read_vectorized(
    header: VcfHeader,
    payload: bytes,
    lo: int,
    hi: int,
    intervals,
) -> Optional["VariantBatch"]:
    """One-pass vectorized tokenizer for the VCF hot path (SURVEY §7
    stage 8): a newline scan builds the line table, one tab scan builds the
    8-column field table, and CHROM→contig-index, POS, REF-length and the
    64-bit keys come out as array ops — no per-line Python.

    Returns None when any line needs the exact per-line parser: structural
    problems (missing tabs, non-digit POS, unusual QUAL/ALT syntax) or a
    CHROM outside the header dictionary (murmur3 key fallback).  The
    VariantContext rows themselves stay lazy (materialized from the line
    table only if a consumer asks)."""
    from .text import MAX_LINE_LENGTH, gather_padded, line_table

    a = np.frombuffer(payload, np.uint8)
    if lo > 0:
        # Split resync: drop the (possibly partial) first line, exactly as
        # SplitLineReader does — a mid-line fragment can otherwise pass
        # the structural screen and emit a spurious variant.
        nl = payload.find(b"\n", lo - 1)
        lo = len(payload) if nl < 0 else nl + 1
        if lo >= hi:
            return VariantBatch(header=header)
    starts, lens = line_table(a, lo, hi)
    keep = (lens > 0) & (a[np.minimum(starts, len(a) - 1)] != 0x23)
    starts, lens = starts[keep], lens[keep]
    n = len(starts)
    if n == 0:
        return VariantBatch(header=header)
    line_end = starts + lens
    # A line cut off by line_table's bounded scan window (giant-cohort
    # rows) must not be materialized half-parsed: bail to the exact path,
    # whose reader walks to the real newline.
    window_end = min(len(a), hi + 4 * (MAX_LINE_LENGTH + 1))
    if window_end < len(a) and bool((line_end >= window_end).any()):
        return None

    # ---- field table: the k-th tab of line i ---------------------------
    wlo, whi = int(starts[0]), int(line_end.max())
    tabs = wlo + np.nonzero(a[wlo:whi] == 0x09)[0]
    t0 = np.searchsorted(tabs, starts)
    tk = t0[:, None] + np.arange(7)
    if len(tabs) == 0:
        return None
    exists = tk < len(tabs)
    T = tabs[np.minimum(tk, len(tabs) - 1)]
    if not (exists & (T < line_end[:, None])).all():
        return None  # a line with < 8 fields: exact error text needed
    fstart = np.concatenate([starts[:, None], T + 1], axis=1)  # field starts
    # INFO ends at the 8th tab when genotype columns follow, else line end.
    tk7 = t0 + 7
    has8 = (tk7 < len(tabs)) & (
        tabs[np.minimum(tk7, len(tabs) - 1)] < line_end
    )
    info_end = np.where(
        has8, tabs[np.minimum(tk7, len(tabs) - 1)], line_end
    )
    fe = np.concatenate([T, info_end[:, None]], axis=1)  # field ends
    flen = fe - fstart

    if (flen[:, 0] == 0).any() or (flen[:, 3] == 0).any():
        return None  # empty CHROM/REF
    # REF length feeds `end` in CHARACTERS (the exact parser's len(str));
    # any non-ASCII byte would make byte length diverge — exact path.
    rlen = flen[:, 3]
    Wr = int(rlen.max())
    rmat = gather_padded(a, fstart[:, 3], rlen, Wr)
    if (rmat >= 0x80).any():
        return None

    # ---- POS: strict [0-9]{1,10} --------------------------------------
    plen = flen[:, 1]
    if (plen == 0).any() or (plen > 10).any():
        return None
    pmat = gather_padded(a, fstart[:, 1], plen, int(plen.max()))
    pdig = pmat - 48
    col = np.arange(pmat.shape[1])[None, :]
    pvalid = col < plen[:, None]
    if ((pdig < 0) | (pdig > 9))[pvalid].any():
        return None
    pos = np.zeros(n, dtype=np.int64)
    for c in range(pmat.shape[1]):
        live = pvalid[:, c]
        pos = np.where(live, pos * 10 + pdig[:, c], pos)

    # ---- QUAL: '.' or empty or [0-9]+(.[0-9]*)? ------------------------
    qlen = flen[:, 5]
    W = int(qlen.max()) if n else 0
    if W:
        qmat = gather_padded(a, fstart[:, 5], qlen, W)
        qcol = np.arange(W)[None, :]
        qvalid = qcol < qlen[:, None]
        is_dot = (qlen == 1) & (qmat[:, 0] == 0x2E)
        plain = qlen == 0
        charset = (~qvalid | _QUAL_OK[qmat]).all(axis=1)
        ndots = ((qmat == 0x2E) & qvalid).sum(axis=1)
        ndigs = ((qmat >= 48) & (qmat <= 57) & qvalid).sum(axis=1)
        numeric = charset & (ndots <= 1) & (ndigs >= 1)
        if not (is_dot | plain | numeric).all():
            return None

    # ---- ALT charset (incl. ',' separators), no empty tokens -----------
    alen = flen[:, 4]
    Wa = int(alen.max()) if n else 0
    if Wa:
        amat = gather_padded(a, fstart[:, 4], alen, Wa)
        acol = np.arange(Wa)[None, :]
        avalid = acol < alen[:, None]
        if (avalid & _ALT_SYM[amat]).any():
            return None  # symbolic/breakend alleles: exact token parser
        if not (~avalid | _ALT_OK[amat]).all():
            return None
        comma = (amat == 0x2C) & avalid
        if comma.any():
            # reject ',,', leading/trailing comma → exact parser decides
            nxt = np.pad(comma[:, 1:], ((0, 0), (0, 1)))
            edge = comma[:, 0:1].any(axis=1) | (
                comma & (acol == (alen - 1)[:, None])
            ).any(axis=1)
            if (comma & nxt).any() or edge.any():
                return None
        if (alen == 0).any():
            return None

    # ---- CHROM → contig index (all must be in the header dict) ---------
    # A split holds few distinct CHROMs; unique-ify the padded rows once
    # and do one dict lookup per distinct name (a per-contig matrix
    # compare would be O(contigs·lines·width) — GRCh38 headers carry
    # thousands of contig lines).
    if not header.contigs:
        return None
    clen = flen[:, 0]
    Wc = int(clen.max())
    cmat = gather_padded(a, fstart[:, 0], clen, Wc)
    if Wc <= 16:
        # Pack each padded row into 1-2 machine words: scalar np.unique is
        # an order of magnitude faster than the axis=0 (row-sort) form.
        packed = np.zeros((n, 16), np.uint8)
        packed[:, :Wc] = cmat
        key2 = packed.view(np.uint64).reshape(n, 2)
        uniq, inv = np.unique(
            key2[:, 0] ^ (key2[:, 1] * np.uint64(0x9E3779B97F4A7C15)),
            return_inverse=True,
        )
        # The xor-mix is only a bucketing key; recover each bucket's name
        # from its first row (collisions across distinct names are broken
        # by re-checking the name text below).
        first_row = np.zeros(len(uniq), np.int64)
        first_row[inv[::-1]] = np.arange(n - 1, -1, -1)
        names = [
            bytes(cmat[r]).rstrip(b"\x00").decode(errors="replace")
            for r in first_row
        ]
        # Guard against (astronomically unlikely) mix collisions: every
        # row in a bucket must equal the bucket's representative row.
        if not (cmat == cmat[first_row[inv]]).all():
            return None
    else:
        uniq_rows, inv = np.unique(cmat, axis=0, return_inverse=True)
        names = [
            bytes(u).rstrip(b"\x00").decode(errors="replace")
            for u in uniq_rows
        ]
    lut = np.empty(len(names), dtype=np.int64)
    for u, name in enumerate(names):
        idx = header._contig_idx.get(name)
        if idx is None:
            return None  # unknown contig: murmur3 key path, exact parser
        lut[u] = idx
    cidx = lut[inv]

    # ---- END: pos + len(REF) - 1, with the INFO END= override ----------
    end = pos + flen[:, 3].astype(np.int64) - 1
    # Lines whose INFO contains an END= key (at the field start or after
    # ';') re-derive end through the exact parser — rare (SV records).
    # Scan only the split's byte window (INFO fields can't point outside).
    w = a[wlo : int(line_end.max())]
    if len(w) >= 4:
        m4 = (
            (w[:-3] == 0x45) & (w[1:-2] == 0x4E)
            & (w[2:-1] == 0x44) & (w[3:] == 0x3D)
        )
        hits = wlo + np.nonzero(m4)[0]
    else:
        hits = np.empty(0, np.int64)
    if len(hits):
        i0 = np.searchsorted(hits, fstart[:, 7])
        i1 = np.searchsorted(hits, fe[:, 7] - 3)
        flagged = np.nonzero(i1 > i0)[0]
        for r in flagged:
            line = bytes(a[starts[r] : line_end[r]]).decode()
            try:
                end[r] = parse_variant_line(line).end
            except FormatException:
                return None

    keys = (cidx << np.int64(32)) | (pos - 1)

    if intervals is not None:
        ivkeep = np.zeros(n, dtype=bool)
        for iv in intervals:
            iv_idx = header._contig_idx.get(iv.contig)
            if iv_idx is None:
                continue  # known-contig lines can't string-match it
            ivkeep |= (
                (cidx == iv_idx) & (pos <= iv.end) & (end >= iv.start)
            )
        starts, line_end = starts[ivkeep], line_end[ivkeep]
        keys, pos, end = keys[ivkeep], pos[ivkeep], end[ivkeep]

    l_starts = starts.copy()
    l_ends = line_end.copy()

    def materialize() -> List[VariantContext]:
        mv = memoryview(payload)
        return [
            parse_variant_line(str(mv[int(s) : int(e)], "utf-8"))
            for s, e in zip(l_starts, l_ends)
        ]

    return VariantBatch(
        header=header,
        keys=keys.astype(np.int64),
        pos=pos.astype(np.int64),
        end=end.astype(np.int64),
        materializer=materialize,
    )


def _header_prefix_text(path: str) -> str:
    """Leading ``#`` header lines of a plain-text VCF via growing prefix
    reads — O(header), not O(file)."""
    from .text import read_header_prefix

    return _header_text(read_header_prefix(path, b"#"))


def _bgzf_header_chunk(data: bytes) -> bytes:
    """Inflate only as many leading BGZF blocks as the header occupies
    (stops once a terminated #CHROM line is present, or the available
    blocks run out)."""
    chunk = bytearray()
    pos = 0
    while pos < len(data):
        try:
            p, csize = bgzf.inflate_block(data, pos)
        except bgzf.BgzfError:
            break
        chunk.extend(p)
        pos += csize
        if b"\n#CHROM" in chunk and b"\n" in chunk[chunk.find(b"\n#CHROM") + 1 :]:
            break
    return bytes(chunk)


def _bgzf_header_text(data: bytes) -> str:
    """Header lines of a BGZF VCF, inflating only as many leading blocks as
    the header occupies."""
    return _header_text(_bgzf_header_chunk(data))


def _header_text(payload: bytes) -> str:
    lines = []
    for raw in payload.split(b"\n"):
        if raw.startswith(b"#"):
            lines.append(raw.decode())
        else:
            break
    return "\n".join(lines)


class VcfRecordWriter:
    """Text VCF writer with swallowed-header part mode and optional BGZF
    output (VCFRecordWriter.java:51-177, KeyIgnoringVCFOutputFormat:93-114).
    """

    def __init__(
        self,
        stream,
        header: VcfHeader,
        write_header: bool = True,
        compress_bgzf: bool = False,
        append_terminator: bool = False,
    ):
        self._compress = compress_bgzf
        if compress_bgzf:
            self._w = bgzf.BgzfWriter(
                stream, append_terminator=append_terminator
            )
        else:
            self._w = stream
        if write_header:
            self._w.write(header.encode())

    def write(self, v: VariantContext) -> None:
        self._w.write(v.format_line().encode() + b"\n")

    def close(self) -> None:
        if self._compress:
            self._w.close()


def merge_vcf_parts(
    part_dir: str,
    out_path: str,
    header: VcfHeader,
    check_success: bool = True,
) -> None:
    """Concatenate headerless parts after the header; block-compressed parts
    get the BGZF terminator appended (util/VCFFileMerger.java:44-134)."""
    if check_success:
        nio.check_success(part_dir)
    parts = nio.list_parts(part_dir)
    first = parts[0].read_bytes() if parts else b""
    if first[:3] == b"BCF":
        raise ValueError("BCF merging is not supported")  # :63-65
    block_compressed = bgzf.is_bgzf(first)
    plain_gzip = not block_compressed and first[:2] == b"\x1f\x8b"
    with open(out_path, "wb") as out:
        hdr_bytes = header.encode()
        if block_compressed:
            w = bgzf.BgzfWriter(out, append_terminator=False)
            w.write(hdr_bytes)
            w.close()
        elif plain_gzip:
            out.write(gzip.compress(hdr_bytes))
        else:
            out.write(hdr_bytes)
        nio.concat_files(parts, out)
        if block_compressed:
            out.write(bgzf.TERMINATOR)


def read_vcf_header(path: str) -> VcfHeader:
    """Header from VCF / gz-VCF / BGZF-VCF / BCF without knowing which
    (try-VCF-then-BCF, util/VCFHeaderReader.java:51-78)."""
    with open(path, "rb") as f:
        raw = f.read(1 << 22)
    probe = raw
    if bgzf.is_bgzf(raw):
        try:
            probe = bgzf.inflate_block(raw, 0)[0]
        except bgzf.BgzfError:
            probe = raw
    if probe[:3] == b"BCF":
        from .bcf import read_bcf_header

        return read_bcf_header(raw)[0].vcf
    if raw[:2] == b"\x1f\x8b":
        if bgzf.is_bgzf(raw):
            chunk = bytearray()
            pos = 0
            while pos < len(raw):
                try:
                    p, csize = bgzf.inflate_block(raw, pos)
                except bgzf.BgzfError:
                    break
                chunk.extend(p)
                pos += csize
                if b"\n#CHROM" in chunk:
                    break
            raw = bytes(chunk)
        else:
            raw = gzip.decompress(open(path, "rb").read())
    return VcfHeader.parse(_header_text(raw))
