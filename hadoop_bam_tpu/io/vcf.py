"""VCF input/output: dispatch, splittable planning, batched reading, merge.

Reference parity:
- format dispatch by extension then content sniff — gunzip if needed, first
  byte 'B' (BCF magic) vs '#' (VCFFormat.java:57-72; trust-exts via
  ``hadoopbam.vcf.trust-exts``),
- splittability: plain text → byte splits; ``.gz``/``.bgz`` only when really
  BGZF (VCFInputFormat.java:198-224); plain gzip is one unsplittable split,
- tabix-index interval filtering of splits (VCFInputFormat.java:387-471) and
  per-record overlap filtering (VCFRecordReader.java:196-217),
- validation stringency STRICT/LENIENT/SILENT
  (``hadoopbam.vcfrecordreader.validation-stringency``,
  VCFRecordReader.java:80-92,180-194),
- writer with swallowed-header part mode (VCFRecordWriter.java:152-177) and
  the part merger incl. the BCF-unsupported guard
  (util/VCFFileMerger.java:44-134),
- VCFHeaderReader: try-VCF-then-BCF header sniffing
  (util/VCFHeaderReader.java:51-78).
"""

from __future__ import annotations

import gzip
import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..conf import (
    Configuration,
    VCF_INTERVALS,
    VCF_TRUST_EXTS,
    VCFRECORDREADER_VALIDATION_STRINGENCY,
)
from ..spec import bgzf, indices
from . import fs
from ..spec.vcf import (
    FormatException,
    VariantContext,
    VcfHeader,
    parse_variant_line,
    variant_key,
)
from ..utils import nio
from ..utils.intervals import Interval, parse_intervals
from .splits import ByteSplit
from .text import SplitLineReader


def sniff_vcf_format(path: str, trust_exts: bool = True) -> Optional[str]:
    """'vcf' | 'bcf' | None (VCFFormat.java:38-72 semantics)."""
    if trust_exts:
        if path.endswith(".vcf") or path.endswith(".vcf.gz") or path.endswith(".vcf.bgz") or path.endswith(".vcf.bgzf.gz"):
            return "vcf"
        if path.endswith(".bcf"):
            return "bcf"
    head = fs.get_fs(path).read_range(path, 0, 1 << 16)
    if head[:2] == b"\x1f\x8b":
        try:
            head = (
                bgzf.inflate_block(head, 0)[0]
                if bgzf.is_bgzf(head)
                else gzip.decompress(head)
            )
        except Exception:
            return None
    if head[:1] == b"B" and head[:3] == b"BCF":
        return "bcf"
    if head[:1] == b"#":
        return "vcf"
    return None


@dataclass
class VariantBatch:
    """Decoded split: variants + int64 keys (SoA columns for device use)."""

    header: VcfHeader
    variants: List[VariantContext]
    keys: np.ndarray  # int64
    pos: np.ndarray  # int64 1-based starts
    end: np.ndarray  # int64 inclusive ends

    @property
    def n_records(self) -> int:
        return len(self.variants)


class VcfInputFormat:
    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()

    # -- stringency (VCFRecordReader.java:80-92) ----------------------------

    def _stringency(self) -> str:
        s = (
            self.conf.get(VCFRECORDREADER_VALIDATION_STRINGENCY, "STRICT")
            or "STRICT"
        ).upper()
        if s not in ("STRICT", "LENIENT", "SILENT"):
            raise ValueError(f"invalid validation stringency {s}")
        return s

    def _intervals(self) -> Optional[List[Interval]]:
        return parse_intervals(self.conf.get(VCF_INTERVALS))

    # -- planning -----------------------------------------------------------

    def get_splits(self, paths, split_size: int = 4 << 20):
        """Partition by sniffed format and delegate BCF files to the BCF
        planner (VCFInputFormat.java:271-297); returns a mixed list of
        ByteSplit (VCF) and FileVirtualSplit (BCF)."""
        trust = self.conf.get_boolean(VCF_TRUST_EXTS, True)
        bcf_paths = [p for p in paths if sniff_vcf_format(p, trust) == "bcf"]
        if bcf_paths:
            from .bcf import BcfInputFormat

            sub = BcfInputFormat(self.conf)
            rest = [p for p in paths if p not in bcf_paths]
            mixed = list(sub.get_splits(bcf_paths, split_size))
            if rest:
                mixed += self.get_splits(rest, split_size)
            return mixed
        out: List[ByteSplit] = []
        for path in sorted(paths):
            pfs = fs.get_fs(path)
            size = pfs.size(path)
            head = pfs.read_range(path, 0, 18)
            if head[:2] == b"\x1f\x8b":
                if bgzf.parse_block_header(head + b"\x00" * 64, 0) or bgzf.is_bgzf(
                    pfs.read_range(path, 0, 1 << 16)
                ):
                    # BGZF: splittable on compressed offsets, snapped to
                    # block boundaries at read time.
                    out.extend(
                        ByteSplit(path, s, min(split_size, size - s))
                        for s in range(0, size, split_size)
                    )
                else:
                    # plain gzip: unsplittable (VCFInputFormat.java:216-221)
                    out.append(ByteSplit(path, 0, size))
            else:
                out.extend(
                    ByteSplit(
                        path, s, min(split_size, size - s), compressed=False
                    )
                    for s in range(0, size, split_size)
                )
        ivs = self._intervals()
        if ivs is not None:
            out = self.filter_by_interval(out, ivs)
        return out

    def filter_by_interval(
        self, splits: List[ByteSplit], intervals: List[Interval]
    ) -> List[ByteSplit]:
        """Drop splits whose tabix chunk spans miss every interval
        (VCFInputFormat.java:387-471).  Files without a .tbi are kept whole
        (warn-and-keep in the reference)."""
        out: List[ByteSplit] = []
        for s in splits:
            tbi_path = s.path + ".tbi"
            if not os.path.exists(tbi_path):
                out.append(s)
                continue
            tbi = indices.Tabix.load(tbi_path)
            keep = False
            for iv in intervals:
                for c in tbi.query(iv.contig, iv.start - 1, iv.end):
                    c_beg, c_end = c.beg >> 16, c.end >> 16
                    if c_beg < s.end and c_end >= s.start:
                        keep = True
                        break
                if keep:
                    break
            if keep:
                out.append(s)
        return out

    # -- reading ------------------------------------------------------------

    def read_split(
        self, split, data: Optional[bytes] = None
    ) -> VariantBatch:
        """Decode every variant whose line starts inside the split.  BCF
        splits (FileVirtualSplit) route to the BCF reader."""
        from .splits import FileVirtualSplit

        if isinstance(split, FileVirtualSplit):
            from .bcf import BcfInputFormat

            return BcfInputFormat(self.conf).read_split(split, data)
        header_text, payload, lo, hi = self._split_payload(split, data)
        header = VcfHeader.parse(header_text)
        stringency = self._stringency()
        intervals = self._intervals()
        reader = SplitLineReader(payload, lo, hi)
        variants: List[VariantContext] = []
        for _, line in reader.lines():
            if not line or line.startswith(b"#"):
                continue
            try:
                v = parse_variant_line(line.decode())
            except FormatException:
                if stringency == "STRICT":
                    raise
                continue  # LENIENT/SILENT skip (:180-194)
            if intervals is not None and not any(
                iv.overlaps(v.chrom, v.start, v.end) for iv in intervals
            ):
                continue
            variants.append(v)
        keys = np.array(
            [variant_key(header, v) for v in variants], dtype=np.int64
        )
        pos = np.array([v.pos for v in variants], dtype=np.int64)
        end = np.array([v.end for v in variants], dtype=np.int64)
        return VariantBatch(
            header=header, variants=variants, keys=keys, pos=pos, end=end
        )

    def _split_payload(
        self, split: ByteSplit, data: Optional[bytes]
    ) -> Tuple[str, bytes, int, int]:
        """(header_text, text_payload, line_scan_start, line_scan_end).

        Without a preloaded buffer the read is split-local: plain text
        reads only the split's window (+ margins), BGZF reads a bounded
        raw window and inflates just the blocks overlapping the split
        (guesser-anchored chain — the BGZFCodec+BGZFSplitGuesser path).
        Plain gzip is unsplittable and falls back to the whole payload.
        """
        if data is None:
            f = fs.get_fs(split.path)
            # Same classification get_splits used (a BGZF BC subfield may
            # sit beyond byte 18 when other extra fields precede it, so an
            # 18-byte sniff under-detects BGZF and would misroute a
            # splittable file to the whole-gzip path).
            head = f.read_range(split.path, 0, 1 << 16)
            is_bgzf_file = head[:2] == b"\x1f\x8b" and (
                bgzf.parse_block_header(head, 0) is not None
                or bgzf.is_bgzf(head)
            )
            if is_bgzf_file:
                return self._bgzf_split_payload(split, f)
            if head[:2] == b"\x1f\x8b":
                data = f.read_all(split.path)  # plain gzip: whole file
            else:
                from .text import read_split_window

                window, rsplit = read_split_window(split)
                return (
                    _header_prefix_text(split.path),
                    window,
                    rsplit.start,
                    rsplit.end,
                )
        if data[:2] == b"\x1f\x8b" and not bgzf.is_bgzf(data):
            payload = gzip.decompress(data)
            return _header_text(payload), payload, split.start, len(payload)
        if bgzf.is_bgzf(data):
            # Snap [start, end) to BGZF blocks (the BGZFCodec+guesser path,
            # util/BGZFCodec.java:56-63).  The previous block is inflated too
            # so the standard skip-partial-first-line protocol sees whether
            # local offset 0 really starts a line; one extra trailing block
            # completes the last straddling line.
            import bisect

            htext = _bgzf_header_text(data)
            blocks = bgzf.scan_blocks(data)
            starts = [b.coffset for b in blocks]
            i0 = bisect.bisect_left(starts, split.start)
            i1 = bisect.bisect_left(starts, split.end)
            if i0 >= i1:
                return htext, b"", 0, 0  # no block starts inside this split

            def inflate(i: int) -> bytes:
                return bgzf.inflate_block(data, blocks[i].coffset)[0]

            prev = inflate(i0 - 1) if i0 > 0 else b""
            mine = b"".join(inflate(i) for i in range(i0, i1))
            extra = inflate(i1) if i1 < len(blocks) else b""
            chunk = prev + mine + extra
            return htext, chunk, len(prev), len(prev) + len(mine)
        return _header_text(data), data, split.start, split.end

    def _bgzf_split_payload(
        self, split: ByteSplit, f
    ) -> Tuple[str, bytes, int, int]:
        """Split-local BGZF VCF: inflate only the blocks overlapping the
        split, located by walking the block chain from a CRC-verified
        guessed boundary inside a bounded raw window (blocks are ≤64KiB,
        so a 2·64KiB back-margin always contains a block start; the
        forward margin covers the one-extra-block line-completion rule)."""
        from .guesser import guess_bgzf_block_start

        size = f.size(split.path)
        end = min(split.end, size)
        w0 = max(0, split.start - 2 * 0xFFFF)
        w1 = min(size, end + 4 * 0xFFFF)
        window = f.read_range(split.path, w0, w1 - w0)
        # Growing prefix reads until the inflated header is complete — a
        # *terminated* #CHROM line (an unterminated fragment would silently
        # drop trailing sample columns on large cohorts) — O(header) bytes.
        n = 1 << 20
        while True:
            prefix = (
                window if w0 == 0 and size <= len(window)
                else f.read_range(split.path, 0, min(n, size))
            )
            chunk = _bgzf_header_chunk(prefix)
            i = chunk.find(b"\n#CHROM")
            if (i >= 0 and chunk.find(b"\n", i + 1) >= 0) or n >= size:
                htext = _header_text(bytes(chunk))
                break
            n *= 4
        # Walk the chain from the first verified boundary in the window.
        at = 0 if w0 == 0 else guess_bgzf_block_start(window, 0, len(window))
        if at is None or w0 + at >= end:
            return htext, b"", 0, 0
        prev = b""
        mine: List[bytes] = []
        extra = b""
        pos = at
        while pos < len(window):
            try:
                payload, csize = bgzf.inflate_block(window, pos)
            except bgzf.BgzfError:
                break  # window truncated mid-block: chain is complete
            abs_off = w0 + pos
            if abs_off < split.start:
                prev = payload  # only the last pre-split block is kept
            elif abs_off < end:
                mine.append(payload)
            else:
                extra = payload  # one block past the split end
                break
            pos += csize
        if not mine:
            return htext, b"", 0, 0
        body = b"".join(mine)
        chunk = prev + body + extra
        return htext, chunk, len(prev), len(prev) + len(body)


def _header_prefix_text(path: str) -> str:
    """Leading ``#`` header lines of a plain-text VCF via growing prefix
    reads — O(header), not O(file)."""
    from .text import read_header_prefix

    return _header_text(read_header_prefix(path, b"#"))


def _bgzf_header_chunk(data: bytes) -> bytes:
    """Inflate only as many leading BGZF blocks as the header occupies
    (stops once a terminated #CHROM line is present, or the available
    blocks run out)."""
    chunk = bytearray()
    pos = 0
    while pos < len(data):
        try:
            p, csize = bgzf.inflate_block(data, pos)
        except bgzf.BgzfError:
            break
        chunk.extend(p)
        pos += csize
        if b"\n#CHROM" in chunk and b"\n" in chunk[chunk.find(b"\n#CHROM") + 1 :]:
            break
    return bytes(chunk)


def _bgzf_header_text(data: bytes) -> str:
    """Header lines of a BGZF VCF, inflating only as many leading blocks as
    the header occupies."""
    return _header_text(_bgzf_header_chunk(data))


def _header_text(payload: bytes) -> str:
    lines = []
    for raw in payload.split(b"\n"):
        if raw.startswith(b"#"):
            lines.append(raw.decode())
        else:
            break
    return "\n".join(lines)


class VcfRecordWriter:
    """Text VCF writer with swallowed-header part mode and optional BGZF
    output (VCFRecordWriter.java:51-177, KeyIgnoringVCFOutputFormat:93-114).
    """

    def __init__(
        self,
        stream,
        header: VcfHeader,
        write_header: bool = True,
        compress_bgzf: bool = False,
        append_terminator: bool = False,
    ):
        self._compress = compress_bgzf
        if compress_bgzf:
            self._w = bgzf.BgzfWriter(
                stream, append_terminator=append_terminator
            )
        else:
            self._w = stream
        if write_header:
            self._w.write(header.encode())

    def write(self, v: VariantContext) -> None:
        self._w.write(v.format_line().encode() + b"\n")

    def close(self) -> None:
        if self._compress:
            self._w.close()


def merge_vcf_parts(
    part_dir: str,
    out_path: str,
    header: VcfHeader,
    check_success: bool = True,
) -> None:
    """Concatenate headerless parts after the header; block-compressed parts
    get the BGZF terminator appended (util/VCFFileMerger.java:44-134)."""
    if check_success:
        nio.check_success(part_dir)
    parts = nio.list_parts(part_dir)
    first = parts[0].read_bytes() if parts else b""
    if first[:3] == b"BCF":
        raise ValueError("BCF merging is not supported")  # :63-65
    block_compressed = bgzf.is_bgzf(first)
    plain_gzip = not block_compressed and first[:2] == b"\x1f\x8b"
    with open(out_path, "wb") as out:
        hdr_bytes = header.encode()
        if block_compressed:
            w = bgzf.BgzfWriter(out, append_terminator=False)
            w.write(hdr_bytes)
            w.close()
        elif plain_gzip:
            out.write(gzip.compress(hdr_bytes))
        else:
            out.write(hdr_bytes)
        nio.concat_files(parts, out)
        if block_compressed:
            out.write(bgzf.TERMINATOR)


def read_vcf_header(path: str) -> VcfHeader:
    """Header from VCF / gz-VCF / BGZF-VCF / BCF without knowing which
    (try-VCF-then-BCF, util/VCFHeaderReader.java:51-78)."""
    with open(path, "rb") as f:
        raw = f.read(1 << 22)
    probe = raw
    if bgzf.is_bgzf(raw):
        try:
            probe = bgzf.inflate_block(raw, 0)[0]
        except bgzf.BgzfError:
            probe = raw
    if probe[:3] == b"BCF":
        from .bcf import read_bcf_header

        return read_bcf_header(raw)[0].vcf
    if raw[:2] == b"\x1f\x8b":
        if bgzf.is_bgzf(raw):
            chunk = bytearray()
            pos = 0
            while pos < len(raw):
                try:
                    p, csize = bgzf.inflate_block(raw, pos)
                except bgzf.BgzfError:
                    break
                chunk.extend(p)
                pos += csize
                if b"\n#CHROM" in chunk:
                    break
            raw = bytes(chunk)
        else:
            raw = gzip.decompress(open(path, "rb").read())
    return VcfHeader.parse(_header_text(raw))
