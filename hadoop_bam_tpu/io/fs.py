"""Filesystem seam: scheme-dispatched byte-range I/O under every reader.

The reference reaches non-local storage through two bridges — Hadoop
``FSDataInputStream`` wrapped as an htsjdk stream (util/WrapSeekable.java:42-66)
and jsr203 NIO paths (util/NIOFileUtil.java:31-55) — so the same record
readers serve ``file:``, ``hdfs:`` and anything else with a provider.  This
module is that seam for the TPU build: every reader asks :func:`get_fs` for
the path's filesystem and does byte-range reads through it, so a GCS/HDFS
adapter is one ``register_filesystem`` call away and no reader changes.

Built-ins: the local filesystem (no scheme, or ``file://``) and an in-memory
``mem://`` filesystem — the cross-scheme round-trip proof used by the tests
and the template for writing a real remote adapter.
"""

from __future__ import annotations

import io
import os
import re
import threading
from typing import BinaryIO, Dict, List, Optional

_SCHEME_RE = re.compile(r"^([A-Za-z][A-Za-z0-9+.-]*)://")


def path_scheme(path: str) -> str:
    """URI scheme of ``path``, or '' for plain local paths."""
    m = _SCHEME_RE.match(path)
    return m.group(1).lower() if m else ""


class Filesystem:
    """Byte-range file access for one URI scheme (WrapSeekable's role).

    Adapters implement the three primitives (``size``, ``read_range``,
    ``open_write``); everything else has default implementations on top.
    Paths arrive as full URIs — the adapter strips its own scheme.
    """

    def size(self, path: str) -> int:
        raise NotImplementedError

    def read_range(self, path: str, start: int, length: int) -> bytes:
        """Bytes ``[start, start+length)``; short reads only at EOF."""
        raise NotImplementedError

    def open_write(self, path: str) -> BinaryIO:
        raise NotImplementedError

    # -- defaults ----------------------------------------------------------
    def read_all(self, path: str) -> bytes:
        return self.read_range(path, 0, self.size(path))

    def exists(self, path: str) -> bool:
        try:
            self.size(path)
            return True
        except (OSError, KeyError, FileNotFoundError):
            return False

    def open_read(self, path: str) -> BinaryIO:
        return io.BytesIO(self.read_all(path))


class LocalFilesystem(Filesystem):
    """Plain OS files; accepts bare paths and ``file://`` URIs."""

    @staticmethod
    def _strip(path: str) -> str:
        return path[7:] if path.startswith("file://") else path

    def size(self, path: str) -> int:
        return os.path.getsize(self._strip(path))

    def read_range(self, path: str, start: int, length: int) -> bytes:
        with open(self._strip(path), "rb") as f:
            f.seek(start)
            return f.read(length)

    def read_all(self, path: str) -> bytes:
        with open(self._strip(path), "rb") as f:
            return f.read()

    def open_read(self, path: str) -> BinaryIO:
        return open(self._strip(path), "rb")

    def open_write(self, path: str) -> BinaryIO:
        return open(self._strip(path), "wb")

    def exists(self, path: str) -> bool:
        return os.path.exists(self._strip(path))


class _MemWriteStream(io.BytesIO):
    def __init__(self, fs: "MemFilesystem", path: str):
        super().__init__()
        self._fs = fs
        self._path = path

    def close(self) -> None:
        if not self.closed:
            self._fs._files[self._path] = self.getvalue()
        super().close()


class MemFilesystem(Filesystem):
    """In-memory filesystem (``mem://``): the non-local round-trip proof
    and the adapter template — a GCS/HDFS adapter implements exactly these
    three primitives against its client library."""

    def __init__(self) -> None:
        self._files: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def size(self, path: str) -> int:
        try:
            return len(self._files[path])
        except KeyError:
            raise FileNotFoundError(path)

    def read_range(self, path: str, start: int, length: int) -> bytes:
        try:
            blob = self._files[path]
        except KeyError:
            raise FileNotFoundError(path)
        return blob[start : start + length]

    def open_write(self, path: str) -> BinaryIO:
        with self._lock:
            return _MemWriteStream(self, path)

    def listdir(self, prefix: str) -> List[str]:
        return sorted(p for p in self._files if p.startswith(prefix))


_LOCAL = LocalFilesystem()
_REGISTRY: Dict[str, Filesystem] = {"": _LOCAL, "file": _LOCAL}
_REG_LOCK = threading.Lock()


def register_filesystem(scheme: str, fs: Filesystem) -> None:
    """Install an adapter for ``scheme`` (e.g. 'gs', 'hdfs', 'mem')."""
    with _REG_LOCK:
        _REGISTRY[scheme.lower()] = fs


def get_fs(path: str) -> Filesystem:
    scheme = path_scheme(path)
    try:
        return _REGISTRY[scheme]
    except KeyError:
        raise ValueError(
            f"no filesystem registered for scheme {scheme!r} "
            f"(path {path!r}); call register_filesystem()"
        )
