"""Filesystem seam: scheme-dispatched byte-range I/O under every reader.

The reference reaches non-local storage through two bridges — Hadoop
``FSDataInputStream`` wrapped as an htsjdk stream (util/WrapSeekable.java:42-66)
and jsr203 NIO paths (util/NIOFileUtil.java:31-55) — so the same record
readers serve ``file:``, ``hdfs:`` and anything else with a provider.  This
module is that seam for the TPU build: every reader asks :func:`get_fs` for
the path's filesystem and does byte-range reads through it, so a GCS/HDFS
adapter is one ``register_filesystem`` call away and no reader changes.

Built-ins: the local filesystem (no scheme, or ``file://``) and an in-memory
``mem://`` filesystem — the cross-scheme round-trip proof used by the tests
and the template for writing a real remote adapter.
"""

from __future__ import annotations

import io
import os
import re
import threading
import time
from typing import BinaryIO, Dict, List, Optional

from .. import faults
from ..utils.tracing import METRICS

_SCHEME_RE = re.compile(r"^([A-Za-z][A-Za-z0-9+.-]*)://")


def _inject_read(path: str, start: int, data: bytes) -> bytes:
    """The byte-I/O fault seam: every local read funnels its result
    through the armed plan (bit-flips, short reads, transient IOError).
    One ``is None`` check when disarmed — nothing else."""
    if faults.ACTIVE is not None:
        return faults.ACTIVE.io_read(path, start, data)
    return data


def read_range_retry(
    filesystem: "Filesystem",
    path: str,
    start: int,
    length: int,
    retries: int = 2,
    backoff_s: float = 0.01,
) -> bytes:
    """A ranged read with bounded retries on transient ``OSError`` — the
    split readers' stance toward flaky devices (HttpFilesystem already
    retries internally; this gives local/remote adapters the same grace).
    Counts ``io.read_retries`` only when a retry actually happens, so a
    clean run's ledger is untouched."""
    for attempt in range(retries + 1):
        try:
            return filesystem.read_range(path, start, length)
        except OSError:
            if attempt == retries:
                raise
            METRICS.count("io.read_retries", 1)
            time.sleep(backoff_s * (2 ** attempt))
    raise AssertionError("unreachable")


def path_scheme(path: str) -> str:
    """URI scheme of ``path``, or '' for plain local paths."""
    m = _SCHEME_RE.match(path)
    return m.group(1).lower() if m else ""


class Filesystem:
    """Byte-range file access for one URI scheme (WrapSeekable's role).

    Adapters implement the three primitives (``size``, ``read_range``,
    ``open_write``); everything else has default implementations on top.
    Paths arrive as full URIs — the adapter strips its own scheme.
    """

    def size(self, path: str) -> int:
        raise NotImplementedError

    def read_range(self, path: str, start: int, length: int) -> bytes:
        """Bytes ``[start, start+length)``; short reads only at EOF."""
        raise NotImplementedError

    def open_write(self, path: str) -> BinaryIO:
        raise NotImplementedError

    # -- defaults ----------------------------------------------------------
    def read_all(self, path: str) -> bytes:
        return self.read_range(path, 0, self.size(path))

    def exists(self, path: str) -> bool:
        try:
            self.size(path)
            return True
        except (OSError, KeyError, FileNotFoundError):
            return False

    def open_read(self, path: str) -> BinaryIO:
        return io.BytesIO(self.read_all(path))


class LocalFilesystem(Filesystem):
    """Plain OS files; accepts bare paths and ``file://`` URIs."""

    @staticmethod
    def _strip(path: str) -> str:
        return path[7:] if path.startswith("file://") else path

    def size(self, path: str) -> int:
        return os.path.getsize(self._strip(path))

    def read_range(self, path: str, start: int, length: int) -> bytes:
        with open(self._strip(path), "rb") as f:
            f.seek(start)
            return _inject_read(path, start, f.read(length))

    def read_all(self, path: str) -> bytes:
        with open(self._strip(path), "rb") as f:
            return _inject_read(path, 0, f.read())

    def open_read(self, path: str) -> BinaryIO:
        return open(self._strip(path), "rb")

    def open_write(self, path: str) -> BinaryIO:
        return open(self._strip(path), "wb")

    def exists(self, path: str) -> bool:
        return os.path.exists(self._strip(path))


class _MemWriteStream(io.BytesIO):
    def __init__(self, fs: "MemFilesystem", path: str):
        super().__init__()
        self._fs = fs
        self._path = path

    def close(self) -> None:
        if not self.closed:
            self._fs._files[self._path] = self.getvalue()
        super().close()


class MemFilesystem(Filesystem):
    """In-memory filesystem (``mem://``): the non-local round-trip proof
    and the adapter template — a GCS/HDFS adapter implements exactly these
    three primitives against its client library."""

    def __init__(self) -> None:
        self._files: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def size(self, path: str) -> int:
        try:
            return len(self._files[path])
        except KeyError:
            raise FileNotFoundError(path)

    def read_range(self, path: str, start: int, length: int) -> bytes:
        try:
            blob = self._files[path]
        except KeyError:
            raise FileNotFoundError(path)
        return blob[start : start + length]

    def open_write(self, path: str) -> BinaryIO:
        with self._lock:
            return _MemWriteStream(self, path)

    def listdir(self, prefix: str) -> List[str]:
        return sorted(p for p in self._files if p.startswith(prefix))


class HttpFilesystem(Filesystem):
    """Read-only HTTP(S) adapter: byte-range reads over ``Range`` headers.

    The reference reads remote storage through Hadoop streams
    (util/WrapSeekable.java:56-66); here any HTTP server that honors
    range requests (object stores, dataset mirrors, ``http.server`` in
    tests) serves split-local reads through the same seam.  Servers that
    ignore ``Range`` (status 200) still work — the response is sliced
    host-side, trading bandwidth for compatibility.

    ``headers`` ride every request (e.g. auth tokens); ``timeout`` is per
    request, and transient failures retry ``retries`` times.  Each retry
    counts ``retry_metric`` (default ``fs.http.retries``) — the retry
    loop used to be silent, which hid flaky byte planes: the multihost
    shuffle fetch passes ``mh.http.fetch_retries`` so its grace shows up
    in the mesh manifests instead of vanishing.
    """

    def __init__(
        self,
        headers: Optional[Dict[str, str]] = None,
        timeout: float = 60.0,
        retries: int = 2,
        retry_metric: str = "fs.http.retries",
    ) -> None:
        self._headers = dict(headers or {})
        self._timeout = timeout
        self._retries = retries
        self._retry_metric = retry_metric
        self._size_cache: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- request plumbing --------------------------------------------------
    def _url(self, path: str) -> str:
        return path

    def _request(self, url: str, method: str, headers: Dict[str, str]):
        """One retried request; the body read happens INSIDE the retry
        loop (a mid-body connection drop on a multi-MB range is the
        dominant transient failure for remote reads, and a response
        object that dies during ``.read()`` can't be retried by the
        caller).  Returns ``(status, headers, body)``; body is ``None``
        for HEAD.  416 (range past EOF) returns ``(416, None, b"")``."""
        import http.client
        import urllib.error
        import urllib.request

        last: Optional[Exception] = None
        for attempt in range(self._retries + 1):
            req = urllib.request.Request(url, method=method)
            for k, v in {**self._headers, **headers}.items():
                req.add_header(k, v)
            try:
                with urllib.request.urlopen(
                    req, timeout=self._timeout
                ) as resp:
                    body = None if method == "HEAD" else resp.read()
                    return resp.status, resp.headers, body
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    raise FileNotFoundError(url) from e
                if e.code == 416:
                    return 416, None, b""
                last = e
                if 400 <= e.code < 500:
                    # Deterministic client errors (401/403/405/…) won't
                    # change on retry — fail fast; 5xx keeps retrying.
                    break
            except (urllib.error.URLError, OSError, http.client.HTTPException) as e:
                last = e
            if attempt < self._retries:
                # A retry is about to happen: count it (the loop used to
                # swallow these — a flaky plane looked identical to a
                # clean one until it finally gave up).
                METRICS.count(self._retry_metric, 1)
        raise OSError(f"HTTP {method} {url} failed: {last}") from last

    # -- the three primitives ----------------------------------------------
    def size(self, path: str) -> int:
        with self._lock:
            if path in self._size_cache:
                return self._size_cache[path]
        url = self._url(path)
        n: Optional[int] = None
        try:
            _, hdrs, _ = self._request(url, "HEAD", {})
            cl = hdrs.get("Content-Length") if hdrs else None
            if cl is not None:
                n = int(cl)
        except FileNotFoundError:
            raise
        except OSError:
            # Servers rejecting HEAD (presigned GET-only URLs: 403/405)
            # still serve ranged GETs — probe with a 1-byte range and
            # parse the Content-Range total instead.
            pass
        if n is None:
            status, hdrs, body = self._request(
                url, "GET", {"Range": "bytes=0-0"}
            )
            cr = hdrs.get("Content-Range") if hdrs else None
            if status == 206:
                total = cr.rsplit("/", 1)[1] if cr and "/" in cr else "*"
                if not total.isdigit():
                    raise OSError(
                        f"cannot determine size of {path}: 206 without a "
                        f"numeric Content-Range total ({cr!r})"
                    )
                n = int(total)
            elif status == 200 and body is not None:
                n = len(body)  # server ignored Range: body is the object
            else:
                raise OSError(f"cannot determine size of {path}")
        with self._lock:
            self._size_cache[path] = n
        return n

    def read_range(self, path: str, start: int, length: int) -> bytes:
        if length <= 0:
            return b""
        end = start + length - 1
        status, _, data = self._request(
            self._url(path), "GET", {"Range": f"bytes={start}-{end}"}
        )
        if data is None:
            return b""
        if status == 200:
            # Server ignored the Range header: slice the full body.
            data = data[start : start + length]
        return data[:length]

    def read_all(self, path: str) -> bytes:
        # One plain GET — the default (HEAD for size, then a ranged GET)
        # costs two round trips per file.
        _, _, data = self._request(self._url(path), "GET", {})
        return data or b""

    def open_write(self, path: str) -> BinaryIO:
        raise OSError(
            f"HttpFilesystem is read-only ({path}); write outputs to a "
            "writable scheme and serve them over HTTP separately"
        )


class GcsFilesystem(HttpFilesystem):
    """GCS adapter skeleton: ``gs://bucket/object`` over the XML API.

    Byte-range reads reuse the HTTP adapter against
    ``{endpoint}/{bucket}/{object}`` (the public-object / signed-proxy
    path); private buckets pass a bearer token.  ``endpoint`` is
    overridable so tests exercise the full gs:// code path against a
    local range-serving HTTP server with zero egress.
    """

    ENDPOINT = "https://storage.googleapis.com"

    def __init__(
        self,
        endpoint: Optional[str] = None,
        token: Optional[str] = None,
        **kw,
    ) -> None:
        headers = kw.pop("headers", {}) or {}
        if token:
            headers["Authorization"] = f"Bearer {token}"
        super().__init__(headers=headers, **kw)
        self._endpoint = (endpoint or self.ENDPOINT).rstrip("/")

    def _url(self, path: str) -> str:
        from urllib.parse import quote

        if path.startswith("gs://"):
            path = path[5:]
        # GCS object names legally contain '#', '?', '%', spaces — all of
        # which urllib would misparse as URL structure if left raw.
        return f"{self._endpoint}/{quote(path, safe='/')}"


_LOCAL = LocalFilesystem()
_REGISTRY: Dict[str, Filesystem] = {
    "": _LOCAL,
    "file": _LOCAL,
    "http": HttpFilesystem(),
    "https": HttpFilesystem(),
}
_REG_LOCK = threading.Lock()


def register_filesystem(scheme: str, fs: Filesystem) -> None:
    """Install an adapter for ``scheme`` (e.g. 'gs', 'hdfs', 'mem')."""
    with _REG_LOCK:
        _REGISTRY[scheme.lower()] = fs


def get_fs(path: str) -> Filesystem:
    scheme = path_scheme(path)
    try:
        return _REGISTRY[scheme]
    except KeyError:
        raise ValueError(
            f"no filesystem registered for scheme {scheme!r} "
            f"(path {path!r}); call register_filesystem()"
        )
