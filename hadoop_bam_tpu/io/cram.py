"""CRAM input: container-aligned split planning (+ container metadata).

Reference semantics (CRAMInputFormat.java): getSplits collects container
start offsets by iterating container headers (:58-70) and snaps each byte
split to the next container boundary (:72-80); the reference source path
comes from ``hadoopbam.cram.reference-source-path`` (:23-24).

Record-level CRAM decode is a declared capability gap this round (the
entropy-codec stack is deferred; SURVEY.md §7 stage 8) — ``read_split``
raises ``CramDecodeUnsupported`` with the container inventory that *is*
available (offsets, per-container record counts — enough for planning and
counting jobs).
"""

from __future__ import annotations

import bisect
import os
from typing import List, Optional

from ..conf import CRAM_REFERENCE_SOURCE_PATH, Configuration
from ..spec import cram
from .splits import ByteSplit


class CramDecodeUnsupported(NotImplementedError):
    pass


class CramInputFormat:
    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()

    def reference_source_path(self) -> Optional[str]:
        return self.conf.get(CRAM_REFERENCE_SOURCE_PATH)

    def get_splits(self, paths, split_size: int = 4 << 20) -> List[ByteSplit]:
        out: List[ByteSplit] = []
        for path in sorted(paths):
            with open(path, "rb") as f:
                data = f.read()
            containers = cram.iter_containers(data)
            # Data containers only: skip the leading CRAM-header container
            # and the EOF container.
            offsets = [
                c.offset
                for c in containers[1:]
                if not c.is_eof
            ]
            if not offsets:
                continue
            size = os.path.getsize(path)
            eof_start = next(
                (c.offset for c in containers if c.is_eof), size
            )
            # Snap byte ranges to container boundaries
            # (CRAMInputFormat.java:72-80).
            for s in range(0, size, split_size):
                e = min(s + split_size, size)
                start = _next_offset(offsets, s)
                end = _next_offset(offsets, e)
                if start is None or start >= eof_start:
                    continue
                end = eof_start if end is None else min(end, eof_start)
                if start < end:
                    out.append(ByteSplit(path, start, end - start))
        return out

    def container_inventory(self, path: str) -> List[cram.ContainerHeader]:
        with open(path, "rb") as f:
            return cram.iter_containers(f.read())

    def count_records(self, split: ByteSplit) -> int:
        """Record count from container headers alone (no decode)."""
        with open(split.path, "rb") as f:
            data = f.read()
        return sum(
            c.n_records
            for c in cram.iter_containers(data)
            if split.start <= c.offset < split.end
        )

    def read_split(self, split: ByteSplit):
        inventory = [
            (c.offset, c.n_records)
            for c in self.container_inventory(split.path)
            if split.start <= c.offset < split.end
        ]
        raise CramDecodeUnsupported(
            "CRAM record decode is not yet implemented in the TPU backend "
            f"(containers in split: {inventory}); container-aligned split "
            "planning and record counting are available"
        )


def _next_offset(offsets: List[int], pos: int) -> Optional[int]:
    i = bisect.bisect_left(offsets, pos)
    return offsets[i] if i < len(offsets) else None
