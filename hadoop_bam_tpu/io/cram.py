"""CRAM input/output: container-aligned splits, record decode, writer.

Reference semantics:
- getSplits collects container start offsets and snaps byte splits to them
  (CRAMInputFormat.java:58-80); the reference FASTA comes from
  ``hadoopbam.cram.reference-source-path`` (:23-24),
- the reader drives record decode across the split's containers
  (CRAMRecordReader.java:43-88),
- the writer emits bare containers, EOF suppressed for parts
  (CRAMRecordWriter.java:98-116); the merger appends it
  (util/SAMFileMerger.java:96-102).

Record decode itself (CRAM 2.1/3.0 codecs, reference-based and no-ref
reconstruction) lives in ``spec/cram.py``.
"""

from __future__ import annotations

import bisect
from typing import Callable, Dict, List, Optional

from ..conf import CRAM_REFERENCE_SOURCE_PATH, Configuration
from ..spec import bam, cram
from . import fs
from .splits import ByteSplit


class ReferenceSource:
    """FASTA reference lookup by reference index (htsjdk ReferenceSource
    role).  Parses the whole FASTA once at construction and caches every
    sequence uppercase in memory."""

    def __init__(self, fasta_path: str):
        self.path = fasta_path
        self._cache: Dict[int, bytes] = {}
        self._names: List[str] = []
        self._load()

    def _load(self) -> None:
        seqs: Dict[str, List[str]] = {}
        name = None
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if line.startswith(">"):
                    name = line[1:].split()[0]
                    self._names.append(name)
                    seqs[name] = []
                elif name is not None:
                    seqs[name].append(line)
        for i, n in enumerate(self._names):
            self._cache[i] = "".join(seqs[n]).upper().encode()

    def get(self, refid: int) -> bytes:
        try:
            return self._cache[refid]
        except KeyError:
            raise cram.CramError(f"reference index {refid} not in FASTA")


class CramInputFormat:
    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()
        self._ref: Optional[ReferenceSource] = None

    def reference_source_path(self) -> Optional[str]:
        return self.conf.get(CRAM_REFERENCE_SOURCE_PATH)

    def _ref_getter(self) -> Optional[Callable[[int], bytes]]:
        if self._ref is None:
            p = self.reference_source_path()
            if p is None:
                return None
            self._ref = ReferenceSource(p)
        return self._ref.get

    def get_splits(self, paths, split_size: int = 4 << 20) -> List[ByteSplit]:
        out: List[ByteSplit] = []
        for path in sorted(paths):
            # Container inventory needs the header chain — one planning
            # pass through the seam (CRAMInputFormat.java:58-70 iterates
            # the whole container stream the same way).
            data = fs.get_fs(path).read_all(path)
            containers = cram.iter_containers(data)
            # Data containers only: skip the leading CRAM-header container
            # and the EOF container.
            offsets = [
                c.offset
                for c in containers[1:]
                if not c.is_eof
            ]
            if not offsets:
                continue
            size = len(data)
            eof_start = next(
                (c.offset for c in containers if c.is_eof), size
            )
            # Snap byte ranges to container boundaries
            # (CRAMInputFormat.java:72-80).
            for s in range(0, size, split_size):
                e = min(s + split_size, size)
                start = _next_offset(offsets, s)
                end = _next_offset(offsets, e)
                if start is None or start >= eof_start:
                    continue
                end = eof_start if end is None else min(end, eof_start)
                if start < end:
                    out.append(ByteSplit(path, start, end - start))
        return out

    def container_inventory(self, path: str) -> List[cram.ContainerHeader]:
        return cram.iter_containers(fs.get_fs(path).read_all(path))

    def count_records(self, split: ByteSplit) -> int:
        """Record count from container headers alone (no decode)."""
        data = fs.get_fs(split.path).read_all(split.path)
        return sum(
            c.n_records
            for c in cram.iter_containers(data)
            if split.start <= c.offset < split.end
        )

    def read_split(
        self,
        split: ByteSplit,
        data: Optional[bytes] = None,
        with_keys: bool = True,
        threads: Optional[int] = None,
        fields: Optional[object] = None,
        device_inflate: Optional[bool] = None,
        inflate_fn=None,
        errors: Optional[str] = None,
        stream=None,
    ):
        """Decode every record of the split's containers into the standard
        RecordBatch (same device pipeline as BAM/SAM).

        Without a preloaded buffer the read is split-local: the CRAM major
        version comes from the 26-byte file definition and only the
        split's own container-aligned byte window is fetched — a split
        costs O(split), not O(file).

        ``stream`` (a DeviceStream) routes block decompression through
        its rANS-lanes tier policy; ``errors="salvage"`` quarantines
        undecodable slices instead of raising.  The BAM-signature kwargs
        (``fields``/``with_keys``/``threads``/``device_inflate``/
        ``inflate_fn``) are accepted so this reader drops into
        ``DeviceStream.read_splits`` unchanged; CRAM decode always
        reconstructs full records, so they are no-ops here."""
        del with_keys, threads, fields, device_inflate, inflate_fn
        from .sam import _records_to_batch

        errors = errors or "strict"
        ref = self._ref_getter()
        records: List[bam.BamRecord] = []
        if data is None:
            f = fs.get_fs(split.path)
            major, _ = cram.parse_file_definition(
                f.read_range(split.path, 0, cram.FILE_DEFINITION_LEN)
            )
            window = f.read_range(split.path, split.start, split.length)
            pos = 0
            while pos < len(window):
                ch = cram.parse_container_header(window, pos, major)
                records.extend(
                    cram.decode_container(
                        window, ch, major, ref,
                        stream=stream, errors=errors,
                    )
                )
                pos = ch.next_offset
            return _records_to_batch(records)
        major, _ = cram.parse_file_definition(data)
        for ch in cram.iter_containers(data):
            if ch.offset < split.start or ch.offset >= split.end:
                continue
            records.extend(
                cram.decode_container(
                    data, ch, major, ref, stream=stream, errors=errors
                )
            )
        return _records_to_batch(records)

    def read_header(self, path: str) -> bam.BamHeader:
        return read_cram_header(path)


def read_cram_header(path_or_bytes) -> bam.BamHeader:
    data = (
        path_or_bytes
        if isinstance(path_or_bytes, (bytes, bytearray))
        else fs.get_fs(path_or_bytes).read_all(path_or_bytes)
    )
    return bam.header_from_text(cram.read_cram_header_text(data))


class CramRecordWriter:
    """Container-stream writer.  ``write_header=False`` omits the file
    definition + header container (headerless parts); ``append_eof=False``
    suppresses the EOF marker so parts can be concatenated
    (CRAMRecordWriter.java:98-116)."""

    def __init__(
        self,
        stream,
        header: bam.BamHeader,
        write_header: bool = True,
        append_eof: bool = False,
        records_per_container: int = 10000,
    ):
        self._stream = stream
        self._header = header
        self._append_eof = append_eof
        self._n_per = records_per_container
        self._pending: List[bam.BamRecord] = []
        self._counter = 0
        if write_header:
            stream.write(cram.MAGIC + bytes([3, 0]) + b"\x00" * 20)
            stream.write(cram.encode_file_header_container(header.text, 3))

    def write_record(self, rec: bam.BamRecord) -> None:
        self._pending.append(rec)
        if len(self._pending) >= self._n_per:
            self._flush()

    def write_batch(self, batch, order=None) -> None:
        idx = order if order is not None else range(batch.n_records)
        for i in idx:
            self.write_record(batch.record(int(i)))

    def _flush(self) -> None:
        if self._pending:
            self._stream.write(
                cram.encode_container(self._pending, self._counter, 3)
            )
            self._counter += len(self._pending)
            self._pending = []

    def close(self) -> None:
        self._flush()
        if self._append_eof:
            self._stream.write(cram.EOF_V3)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _next_offset(offsets: List[int], pos: int) -> Optional[int]:
    i = bisect.bisect_left(offsets, pos)
    return offsets[i] if i < len(offsets) else None
