"""Text-format plumbing: byte splits, line iteration, split resync rules.

The LineReader-layer equivalent (reference LineReader.java fork of Hadoop's):
CR/LF/CRLF handling, plus the classic split protocol — a reader whose split
starts mid-file discards the partial first line and reads one record past
its end so every record belongs to exactly one split
(SAMRecordReader.java:108-146, QseqInputFormat.java:136-155).

Compressed text files are unsplittable (single full-file split), matching
FastqInputFormat.java:393-398 — except BGZF, which the VCF path handles
with virtual splits.
"""

from __future__ import annotations

import gzip
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..spec import bgzf
from . import fs
from .splits import ByteSplit

MAX_LINE_LENGTH = 20000  # reference FastqInputFormat.java MAX_LINE_LENGTH


# ---------------------------------------------------------------------------
# Vectorized tokenization (SURVEY §7 stage 8: "newline scans are trivially
# vectorizable").  These replace per-record Python line loops in the
# FASTQ/QSEQ/VCF hot paths: one pass finds every line, one batched gather
# builds the padded SoA tensors.
# ---------------------------------------------------------------------------


def line_table(
    a: np.ndarray,
    start: int,
    stop: int,
    tail: int = 4 * (MAX_LINE_LENGTH + 1),
) -> Tuple[np.ndarray, np.ndarray]:
    """(starts, lens) of every line beginning in ``[start, stop)`` of the
    uint8 buffer ``a``.

    Lines may end past ``stop`` — the read-past-the-split-end protocol —
    so the scan window extends ``tail`` bytes beyond ``stop`` (enough for
    a full trailing FASTQ record at the reference's MAX_LINE_LENGTH), NOT
    to EOF: per-split cost is O(split), independent of file size.  CR/LF
    terminators are excluded from ``lens``.
    """
    window_end = min(len(a), stop + tail)
    stop = min(stop, window_end)
    nl = start + np.nonzero(a[start:window_end] == 0x0A)[0]
    starts = np.concatenate(([start], nl + 1)).astype(np.int64)
    ends = np.concatenate((nl, [window_end])).astype(np.int64)
    if len(starts) > 1 and starts[-1] >= window_end:
        starts = starts[:-1]
        ends = ends[:-1]
    keep = starts < stop
    starts, ends = starts[keep], ends[keep]
    lens = ends - starts
    # Strip a trailing CR (CRLF files).
    has_cr = (lens > 0) & (a[np.maximum(ends - 1, 0)] == 0x0D)
    lens = lens - has_cr.astype(np.int64)
    return starts, lens


def gather_padded(
    a: np.ndarray,
    starts: np.ndarray,
    lens: np.ndarray,
    width: Optional[int] = None,
    chunk_rows: int = 1 << 16,
) -> np.ndarray:
    """Ragged byte slices → 0-padded uint8[N, width] matrix.

    Chunked fancy-index gather: peak temp is ``chunk_rows*width`` indices,
    not ``N*width`` — 1M-read batches stay cache/RAM friendly.
    """
    n = len(starts)
    W = int(width if width is not None else (lens.max() if n else 0))
    if n and W:
        from .. import native

        # Clamp to EOF (read-past-split protocol can point the final row
        # past the buffer when the file lacks a trailing newline).
        ln_c = np.minimum(lens, len(a) - starts)
        rows = native.gather_rows(a, starts, ln_c, W)
        if rows is not None:
            return rows
    out = np.empty((n, W), dtype=np.uint8)
    if n == 0 or W == 0:
        return out.reshape(n, W)
    col = np.arange(W, dtype=np.int64)[None, :]
    amax = len(a) - 1
    uniform = bool((lens == W).all())
    for r0 in range(0, n, chunk_rows):
        r1 = min(n, r0 + chunk_rows)
        idx = starts[r0:r1, None] + col
        # Only the final rows can index past EOF; everything else skips the
        # clip+mask entirely (the uniform-length fast path is the common
        # case: fixed-length reads).
        tail = int(idx[-1, -1]) > amax
        if tail:
            np.clip(idx, 0, amax, out=idx)
        chunk = a[idx]
        if not uniform:
            chunk[col >= lens[r0:r1, None]] = 0
        elif tail:
            chunk[(starts[r0:r1, None] + col) > amax] = 0
        out[r0:r1] = chunk
    return out


def decode_slices(
    data, starts: np.ndarray, lens: np.ndarray
) -> List[str]:
    """Per-row substrings as Python strs (names/keys stay host-side)."""
    mv = memoryview(data)
    return [
        str(mv[int(s) : int(s + l)], "utf-8")
        for s, l in zip(starts, lens)
    ]


def is_gzip(path: str) -> bool:
    return fs.get_fs(path).read_range(path, 0, 2) == b"\x1f\x8b"


def plan_byte_splits(
    path: str, split_size: int, splittable: Optional[bool] = None
) -> List[ByteSplit]:
    size = fs.get_fs(path).size(path)
    compressed = None
    if splittable is None:
        compressed = is_gzip(path)
        splittable = not compressed
    if not splittable:
        return (
            [ByteSplit(path, 0, size, compressed=compressed)]
            if size
            else []
        )
    return [
        ByteSplit(path, s, min(split_size, size - s), compressed=compressed)
        for s in range(0, size, split_size)
    ]


def read_decompressed(path: str) -> bytes:
    """Whole-file read through the gzip/BGZF codec chain (the
    CompressionCodecFactory role, VCFRecordReader.java:121-131)."""
    raw = fs.get_fs(path).read_all(path)
    if raw[:2] == b"\x1f\x8b":
        if bgzf.is_bgzf(raw):
            return bgzf.decompress_all(raw)
        return gzip.decompress(raw)
    return raw


def read_split_window(
    split: ByteSplit,
    min_lines_past_end: int = 1,
    tail: int = 1 << 16,
) -> Tuple[bytes, ByteSplit]:
    """Split-local bytes of an uncompressed text split + the rebased split.

    Reads only ``[start-1, end+tail')`` — the reference's contract that a
    split costs O(split) bytes, not O(file) (SAMRecordReader.java:108-146
    seeks to ``start-1`` and reads one line past ``end``).  The window
    grows geometrically until ``min_lines_past_end`` newlines lie at/after
    ``end`` (or EOF), so a record that *starts* inside the split always
    completes inside the window (FASTQ needs 4 lines; single-line formats
    1).  Returns ``(window_bytes, split_rebased_to_window_offsets)``.

    A gzip-magic file falls back to the whole decompressed payload (such
    files are unsplittable — the caller holds its single full split).

    Remote-friendly: when the split carries the planner's ``compressed``
    probe result, the only filesystem traffic is the ranged window reads
    themselves (EOF is detected from short reads, no ``size()`` call).
    """
    f = fs.get_fs(split.path)
    compressed = split.compressed
    if compressed is None:
        compressed = f.read_range(split.path, 0, 2) == b"\x1f\x8b"
    if compressed:
        data = read_decompressed(split.path)
        return data, ByteSplit(
            split.path, 0, len(data), compressed=False
        )
    w0 = max(0, split.start - 1)
    end = split.end
    while True:
        w1 = end + tail
        data = f.read_range(split.path, w0, w1 - w0)
        if len(data) < w1 - w0:
            # Short read: the window reached EOF — nothing left to grow
            # into, and the split end clamps to the actual file size.
            end = min(end, w0 + len(data))
            break
        # Enough complete lines past the split end?
        pos = end - w0 - 1  # a terminator exactly at end-1 counts for the
        found = True  # line *ending* at the boundary
        for _ in range(min_lines_past_end):
            at = data.find(b"\n", max(pos, 0))
            if at < 0:
                found = False
                break
            pos = at + 1
        if found:
            break
        tail *= 4
    return data, ByteSplit(
        split.path,
        split.start - w0,
        max(0, end - split.start),
        compressed=False,
    )


def read_header_prefix(path: str, marker: bytes) -> bytes:
    """The leading ``marker``-prefixed header lines of a text file without
    reading the whole file: growing prefix reads until a terminated
    non-header line (or EOF) appears — O(header) bytes.  Gzip input falls
    back to full decompression (such files are unsplittable anyway).

    The shared header re-injection primitive (SAM ``@`` lines per
    SAMRecordReader.java:183-330, VCF ``#`` lines per
    VCFRecordReader.java:111-154)."""
    f = fs.get_fs(path)
    size = f.size(path)
    n = 8 << 10
    while True:
        blob = f.read_range(path, 0, min(n, size))
        if blob[:2] == b"\x1f\x8b":
            return read_decompressed(path)
        pos = 0
        while pos < len(blob) and blob[pos : pos + 1] == marker:
            nl = blob.find(b"\n", pos)
            if nl < 0:
                pos = len(blob)
                break
            pos = nl + 1
        if pos < len(blob) or len(blob) >= size:
            return blob
        n *= 4


class SplitLineReader:
    """Iterate complete lines of one byte split of an uncompressed file.

    A split starting at ``start > 0`` skips the (possibly partial) first
    line; iteration continues past ``end`` to finish the last line that
    *started* inside the split.  Line terminators (LF or CRLF) are stripped,
    as in the reference LineReader (:111-173).
    """

    def __init__(self, data: bytes, start: int, end: int):
        self.data = data
        self.end = end
        if start > 0:
            nl = data.find(b"\n", start - 1)
            self.pos = len(data) if nl < 0 else nl + 1
        else:
            self.pos = 0

    def tell(self) -> int:
        return self.pos

    def at_end(self) -> bool:
        return self.pos >= self.end or self.pos >= len(self.data)

    def read_line(self) -> Optional[bytes]:
        """Next line (terminator stripped) regardless of the split end;
        None at EOF."""
        if self.pos >= len(self.data):
            return None
        nl = self.data.find(b"\n", self.pos)
        if nl < 0:
            line = self.data[self.pos :]
            self.pos = len(self.data)
        else:
            line = self.data[self.pos : nl]
            self.pos = nl + 1
        if line.endswith(b"\r"):
            line = line[:-1]
        return line

    def lines(self) -> Iterator[Tuple[int, bytes]]:
        """(start_offset, line) for every line starting inside the split."""
        while not self.at_end():
            at = self.pos
            line = self.read_line()
            if line is None:
                break
            yield at, line
