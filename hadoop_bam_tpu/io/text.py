"""Text-format plumbing: byte splits, line iteration, split resync rules.

The LineReader-layer equivalent (reference LineReader.java fork of Hadoop's):
CR/LF/CRLF handling, plus the classic split protocol — a reader whose split
starts mid-file discards the partial first line and reads one record past
its end so every record belongs to exactly one split
(SAMRecordReader.java:108-146, QseqInputFormat.java:136-155).

Compressed text files are unsplittable (single full-file split), matching
FastqInputFormat.java:393-398 — except BGZF, which the VCF path handles
with virtual splits.
"""

from __future__ import annotations

import gzip
import os
from typing import Iterator, List, Optional, Tuple

from ..spec import bgzf
from .splits import ByteSplit

MAX_LINE_LENGTH = 20000  # reference FastqInputFormat.java MAX_LINE_LENGTH


def is_gzip(path: str) -> bool:
    with open(path, "rb") as f:
        return f.read(2) == b"\x1f\x8b"


def plan_byte_splits(
    path: str, split_size: int, splittable: Optional[bool] = None
) -> List[ByteSplit]:
    size = os.path.getsize(path)
    if splittable is None:
        splittable = not is_gzip(path)
    if not splittable:
        return [ByteSplit(path, 0, size)] if size else []
    return [
        ByteSplit(path, s, min(split_size, size - s))
        for s in range(0, size, split_size)
    ]


def read_decompressed(path: str) -> bytes:
    """Whole-file read through the gzip/BGZF codec chain (the
    CompressionCodecFactory role, VCFRecordReader.java:121-131)."""
    with open(path, "rb") as f:
        raw = f.read()
    if raw[:2] == b"\x1f\x8b":
        if bgzf.is_bgzf(raw):
            return bgzf.decompress_all(raw)
        return gzip.decompress(raw)
    return raw


class SplitLineReader:
    """Iterate complete lines of one byte split of an uncompressed file.

    A split starting at ``start > 0`` skips the (possibly partial) first
    line; iteration continues past ``end`` to finish the last line that
    *started* inside the split.  Line terminators (LF or CRLF) are stripped,
    as in the reference LineReader (:111-173).
    """

    def __init__(self, data: bytes, start: int, end: int):
        self.data = data
        self.end = end
        if start > 0:
            nl = data.find(b"\n", start - 1)
            self.pos = len(data) if nl < 0 else nl + 1
        else:
            self.pos = 0

    def tell(self) -> int:
        return self.pos

    def at_end(self) -> bool:
        return self.pos >= self.end or self.pos >= len(self.data)

    def read_line(self) -> Optional[bytes]:
        """Next line (terminator stripped) regardless of the split end;
        None at EOF."""
        if self.pos >= len(self.data):
            return None
        nl = self.data.find(b"\n", self.pos)
        if nl < 0:
            line = self.data[self.pos :]
            self.pos = len(self.data)
        else:
            line = self.data[self.pos : nl]
            self.pos = nl + 1
        if line.endswith(b"\r"):
            line = line[:-1]
        return line

    def lines(self) -> Iterator[Tuple[int, bytes]]:
        """(start_offset, line) for every line starting inside the split."""
        while not self.at_end():
            at = self.pos
            line = self.read_line()
            if line is None:
                break
            yield at, line
