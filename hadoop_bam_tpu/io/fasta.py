"""FASTA input: one split per contig, line-granular reference fragments.

Reference semantics (FastaInputFormat.java): getSplits re-reads the file
scanning for ``>`` description lines and emits one split per contig
(:62-154, single-file orientation); the reader keys ``description:position``
and yields one line per value with its contig and 1-based position
(:334-372).  ``ReferenceFragment`` (ReferenceFragment.java) carries
(contig, position, sequence line).

TPU-first: ``read_split`` returns the whole contig's sequence as one uint8
array + per-line offsets, so downstream kernels see a dense base tensor.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from ..conf import Configuration
from .splits import ByteSplit
from .text import SplitLineReader, read_decompressed


@dataclass
class ReferenceFragment:
    contig: str
    position: int  # 1-based coordinate of the first base in this line
    sequence: bytes


@dataclass
class ContigBatch:
    contig: str
    bases: np.ndarray  # uint8, concatenated sequence
    line_offsets: np.ndarray  # int64 offsets of each source line in `bases`
    line_positions: np.ndarray  # int64 1-based coordinate per line

    def fragments(self) -> List[ReferenceFragment]:
        out = []
        ends = list(self.line_offsets[1:]) + [len(self.bases)]
        for off, end, pos in zip(self.line_offsets, ends, self.line_positions):
            out.append(
                ReferenceFragment(
                    self.contig,
                    int(pos),
                    self.bases[int(off) : int(end)].tobytes(),
                )
            )
        return out


class FastaInputFormat:
    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()

    def get_splits(self, paths) -> List[ByteSplit]:
        """One split per contig, found by scanning for '>' lines
        (FastaInputFormat.java:62-154)."""
        out: List[ByteSplit] = []
        for path in sorted(paths):
            data = read_decompressed(path)
            starts = []
            pos = 0
            while True:
                if pos == 0 and data[:1] == b">":
                    starts.append(0)
                    pos = 1
                idx = data.find(b"\n>", pos)
                if idx < 0:
                    break
                starts.append(idx + 1)
                pos = idx + 2
            for i, s in enumerate(starts):
                end = starts[i + 1] if i + 1 < len(starts) else len(data)
                out.append(ByteSplit(path, s, end - s))
        return out

    def read_split(
        self, split: ByteSplit, data: Optional[bytes] = None
    ) -> ContigBatch:
        if data is None:
            data = read_decompressed(split.path)
        r = SplitLineReader(data, 0, split.end)
        r.pos = split.start
        desc_line = r.read_line()
        if desc_line is None or not desc_line.startswith(b">"):
            raise IOError(f"split does not start at a FASTA description: {split}")
        contig = desc_line[1:].split()[0].decode()
        chunks: List[bytes] = []
        offsets: List[int] = []
        positions: List[int] = []
        pos_1based = 1
        total = 0
        while r.pos < split.end:
            line = r.read_line()
            if line is None:
                break
            if line.startswith(b">"):
                break
            if not line:
                continue
            offsets.append(total)
            positions.append(pos_1based)
            chunks.append(line)
            total += len(line)
            pos_1based += len(line)
        bases = (
            np.frombuffer(b"".join(chunks), dtype=np.uint8)
            if chunks
            else np.empty(0, np.uint8)
        )
        return ContigBatch(
            contig=contig,
            bases=bases,
            line_offsets=np.asarray(offsets, dtype=np.int64),
            line_positions=np.asarray(positions, dtype=np.int64),
        )
