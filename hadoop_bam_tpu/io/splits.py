"""Split descriptors: virtual-offset ranges over BGZF files.

The FileVirtualSplit equivalent (reference FileVirtualSplit.java): a split is
``[vstart, vend)`` in virtual-offset space over one file, optionally carrying
interval-filter chunk pointers (FileVirtualSplit.java:91-98) so the reader can
do bounded traversal without re-querying the index.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple


@dataclass
class FileVirtualSplit:
    path: str
    vstart: int  # virtual offset of first record
    vend: int  # virtual offset one past the last record byte
    interval_chunks: Optional[List[Tuple[int, int]]] = None

    @property
    def length_estimate(self) -> int:
        """Approximate byte length via the high 48 bits
        (FileVirtualSplit.java:73-78)."""
        return (self.vend >> 16) - (self.vstart >> 16)

    def __repr__(self) -> str:
        iv = f", chunks={len(self.interval_chunks)}" if self.interval_chunks else ""
        return (
            f"FileVirtualSplit({self.path}, {self.vstart:#x}-{self.vend:#x}{iv})"
        )


@dataclass
class ByteSplit:
    """A plain byte-range split (text formats / uncompressed files).

    ``compressed`` caches the planner's gzip-magic probe so per-split
    readers on remote filesystems skip a head-range round trip; ``None``
    means unknown (the reader probes)."""

    path: str
    start: int
    length: int
    compressed: Optional[bool] = None

    @property
    def end(self) -> int:
        return self.start + self.length
