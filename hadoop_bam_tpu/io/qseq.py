"""QSEQ input/output (QseqInputFormat.java / QseqOutputFormat.java).

11 tab-separated fields per line: machine, run, lane, tile, x, y, index,
read, sequence, quality, filter.  Key = ``machine:run:lane:tile:x:y:read``
(:344-363); ``.`` bases become ``N`` and the index field treats ``0`` as
null (:378-385); default input quality encoding is Illumina Phred+64,
converted to Sanger (:403-426).  Split resync = drop the partial first line
(:136-155).  The writer emits ``N``→``.`` and re-encodes quality
(QseqOutputFormat.java:98-157).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..conf import (
    Configuration,
    INPUT_BASE_QUALITY_ENCODING,
    INPUT_FILTER_FAILED_QC,
    QSEQ_BASE_QUALITY_ENCODING,
    QSEQ_FILTER_FAILED_QC,
    QSEQ_OUTPUT_BASE_QUALITY_ENCODING,
)
from ..spec.fragment import (
    ILLUMINA_MAX,
    ILLUMINA_OFFSET,
    SANGER_MAX,
    SANGER_OFFSET,
    FormatException,
    FragmentBatch,
    SequencedFragment,
    convert_quality,
    verify_quality,
)
from .splits import ByteSplit
from .text import (
    SplitLineReader,
    gather_padded,
    line_table,
    plan_byte_splits,
)

NUM_QSEQ_COLS = 11


def parse_qseq_line(line: bytes) -> tuple[str, SequencedFragment]:
    fields = line.split(b"\t")
    if len(fields) != NUM_QSEQ_COLS:
        raise FormatException(
            f"found {len(fields)} fields instead of 11. Line: {line!r}"
        )
    frag = SequencedFragment()
    frag.instrument = fields[0].decode()
    frag.run_number = int(fields[1])
    frag.lane = int(fields[2])
    frag.tile = int(fields[3])
    frag.xpos = int(fields[4])
    frag.ypos = int(fields[5])
    frag.read = int(fields[7])
    frag.filter_passed = fields[10][:1] != b"0"
    if fields[6][:1] == b"0":  # 0 is a null index sequence (:378-382)
        frag.index_sequence = None
    else:
        frag.index_sequence = fields[6].decode().replace(".", "N")
    frag.sequence = fields[8].replace(b".", b"N")
    frag.quality = bytes(fields[9])
    key = b":".join(fields[0:6] + [fields[7]]).decode()
    return key, frag


def _qseq_materializer(a, cs, ce, qual_lens):
    """Lazy per-record view: metadata from the field table, seq/qual from
    the already-converted SoA tensors."""

    def build(batch):
        out = []
        for i in range(batch.n_records):
            sl = int(batch.lengths[i])
            ql = int(qual_lens[i])
            frag = SequencedFragment(
                sequence=batch.seq[i, :sl].tobytes(),
                quality=batch.qual[i, :ql].tobytes(),
            )
            frag.instrument = bytes(a[cs[i, 0] : ce[i, 0]]).decode()
            frag.run_number = int(bytes(a[cs[i, 1] : ce[i, 1]]))
            frag.lane = int(bytes(a[cs[i, 2] : ce[i, 2]]))
            frag.tile = int(bytes(a[cs[i, 3] : ce[i, 3]]))
            frag.xpos = int(bytes(a[cs[i, 4] : ce[i, 4]]))
            frag.ypos = int(bytes(a[cs[i, 5] : ce[i, 5]]))
            frag.read = int(bytes(a[cs[i, 7] : ce[i, 7]]))
            filt = bytes(a[cs[i, 10] : ce[i, 10]])
            frag.filter_passed = filt[:1] != b"0"
            index = bytes(a[cs[i, 6] : ce[i, 6]])
            if index[:1] == b"0":  # 0 is a null index sequence (:378-382)
                frag.index_sequence = None
            else:
                frag.index_sequence = index.decode().replace(".", "N")
            out.append(frag)
        return out

    return build


class QseqInputFormat:
    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()

    def _encoding(self) -> str:
        enc = self.conf.get(
            QSEQ_BASE_QUALITY_ENCODING,
            self.conf.get(INPUT_BASE_QUALITY_ENCODING, "illumina"),
        )
        if enc not in ("sanger", "illumina"):
            raise ValueError(f"Unknown input base quality encoding value {enc}")
        return enc

    def _filter_failed(self) -> bool:
        raw = self.conf.get(
            QSEQ_FILTER_FAILED_QC, self.conf.get(INPUT_FILTER_FAILED_QC)
        )
        c = Configuration({"k": raw} if raw is not None else None)
        return c.get_boolean("k", False)

    def get_splits(self, paths, split_size: int = 4 << 20) -> List[ByteSplit]:
        out: List[ByteSplit] = []
        for p in sorted(paths):
            out.extend(plan_byte_splits(p, split_size))
        return out

    def read_split(
        self, split: ByteSplit, data: Optional[bytes] = None
    ) -> FragmentBatch:
        """Vectorized split read: one newline scan + one tab scan build the
        11-column field table (per-line tab positions via searchsorted on
        the global tab index); seq/qual land in padded SoA tensors through
        one batched gather.  Metadata fields materialize lazily."""
        if data is None:
            # Split-local window read: O(split) bytes off the filesystem,
            # gzip falling back to the whole (unsplittable) payload.
            from .text import read_split_window

            data, split = read_split_window(split)
        encoding = self._encoding()
        filter_failed = self._filter_failed()
        a = np.frombuffer(data, dtype=np.uint8)
        # Split resync: drop the partial first line (:136-155).
        start = split.start
        if start > 0:
            nl = data.find(b"\n", start - 1) if isinstance(data, bytes) else -1
            if not isinstance(data, bytes):
                hits = np.nonzero(a[start - 1 :] == 0x0A)[0]
                nl = start - 1 + int(hits[0]) if len(hits) else -1
            start = len(a) if nl < 0 else nl + 1
        starts, lens = line_table(a, start, split.end)
        keep = lens > 0  # blank lines are skipped, as in the line loop
        starts, lens = starts[keep], lens[keep]
        n = len(starts)
        if n == 0:
            return FragmentBatch(
                seq=np.zeros((0, 0), np.uint8),
                qual=np.zeros((0, 0), np.uint8),
                lengths=np.zeros(0, np.int32),
                _names=[],
            )
        # Field table: the k-th tab of line i, via one windowed tab scan
        # (O(split), not O(file)).
        wlo = int(starts[0])
        whi = int((starts + lens).max())
        tabs = wlo + np.nonzero(a[wlo:whi] == 0x09)[0]
        t0 = np.searchsorted(tabs, starts)
        tk = t0[:, None] + np.arange(NUM_QSEQ_COLS - 1)
        exists = tk < len(tabs)  # clamping alone must not fake a field
        T = tabs[np.minimum(tk, max(len(tabs) - 1, 0))] if len(tabs) else (
            np.zeros_like(tk)
        )
        in_line = exists & (T < (starts + lens)[:, None])
        bad = ~in_line.all(axis=1)
        # Too many tabs: the 11th field would contain another tab.
        over = np.minimum(t0 + NUM_QSEQ_COLS - 1, max(len(tabs) - 1, 0))
        has11 = (
            (t0 + NUM_QSEQ_COLS - 1 < len(tabs))
            & (tabs[over] < starts + lens)
            if len(tabs)
            else np.zeros(n, dtype=bool)
        )
        bad |= has11
        if bad.any():
            i = int(np.argmax(bad))
            line = bytes(a[starts[i] : starts[i] + lens[i]])
            nfields = int(in_line[i].sum()) + 1 if not has11[i] else 12
            raise FormatException(
                f"found {nfields} fields instead of 11. Line: {line!r}"
            )
        # Column c of line i spans [cs[i,c], ce[i,c]).
        cs = np.concatenate([starts[:, None], T + 1], axis=1)
        ce = np.concatenate([T, (starts + lens)[:, None]], axis=1)
        seq_lens = (ce[:, 8] - cs[:, 8]).astype(np.int64)
        qual_lens = (ce[:, 9] - cs[:, 9]).astype(np.int64)

        if filter_failed:
            # An empty trailing field at EOF has cs == len(a): no byte to
            # read, and the empty field counts as passed (b"" != b"0").
            f10 = np.minimum(cs[:, 10], len(a) - 1)
            passed = (cs[:, 10] >= ce[:, 10]) | (a[f10] != 0x30)  # '0'
            sel = np.nonzero(passed)[0]
            if len(sel) < n:
                starts, lens = starts[sel], lens[sel]
                cs, ce = cs[sel], ce[sel]
                seq_lens, qual_lens = seq_lens[sel], qual_lens[sel]
                n = len(sel)

        W = int(max(seq_lens.max(), qual_lens.max())) if n else 0
        seq = gather_padded(a, cs[:, 8].astype(np.int64), seq_lens, W)
        qual = gather_padded(a, cs[:, 9].astype(np.int64), qual_lens, W)
        smask = np.arange(W)[None, :] < seq_lens[:, None]
        qmask = np.arange(W)[None, :] < qual_lens[:, None]
        seq[smask & (seq == 0x2E)] = ord("N")  # '.' → 'N' (:403-426)

        if encoding == "illumina":
            inr = (qual >= ILLUMINA_OFFSET) & (
                qual <= ILLUMINA_OFFSET + ILLUMINA_MAX
            )
            if bool((qmask & ~inr).any()):
                r, c = np.argwhere(qmask & ~inr)[0]
                raise FormatException(
                    "base quality score out of range for Illumina Phred+64 "
                    f"format (found {int(qual[r, c]) - ILLUMINA_OFFSET} but "
                    f"acceptable range is [0,{ILLUMINA_MAX}]).\n"
                    "Maybe qualities are encoded in Sanger format?\n"
                )
            qual = np.where(
                qmask,
                qual.astype(np.int16) - (ILLUMINA_OFFSET - SANGER_OFFSET),
                0,
            ).astype(np.uint8)
        else:
            inr = (qual >= SANGER_OFFSET) & (qual <= SANGER_OFFSET + SANGER_MAX)
            if bool((qmask & ~inr).any()):
                r, c = np.argwhere(qmask & ~inr)[0]
                raise FormatException(
                    "qseq base quality score out of range for Sanger "
                    f"Phred+33 format (found {int(qual[r, c]) - 33})."
                )

        # Keys: machine:run:lane:tile:x:y:read (:344-363) — decoded lazily
        # would lose the ':' joins, so build once from the column slices.
        mv = memoryview(data) if isinstance(data, bytes) else memoryview(a)
        names = [
            ":".join(
                (
                    str(mv[cs[i, 0] : ce[i, 0]], "utf-8"),
                    str(mv[cs[i, 1] : ce[i, 1]], "utf-8"),
                    str(mv[cs[i, 2] : ce[i, 2]], "utf-8"),
                    str(mv[cs[i, 3] : ce[i, 3]], "utf-8"),
                    str(mv[cs[i, 4] : ce[i, 4]], "utf-8"),
                    str(mv[cs[i, 5] : ce[i, 5]], "utf-8"),
                    str(mv[cs[i, 7] : ce[i, 7]], "utf-8"),
                )
            )
            for i in range(n)
        ]
        return FragmentBatch(
            seq=seq,
            qual=qual,
            lengths=seq_lens.astype(np.int32),
            _names=names,
            materializer=_qseq_materializer(a, cs, ce, qual_lens),
        )


class QseqOutputFormat:
    """Write fragments as QSEQ lines (QseqOutputFormat.java:98-157)."""

    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()
        enc = self.conf.get(QSEQ_OUTPUT_BASE_QUALITY_ENCODING, "illumina")
        if enc not in ("sanger", "illumina"):
            raise ValueError(f"Unknown output base quality encoding {enc}")
        self.encoding = enc

    def format_record(self, frag: SequencedFragment) -> bytes:
        qual = frag.quality
        if self.encoding == "illumina":
            qual = convert_quality(qual, "sanger", "illumina")
        fields = [
            (frag.instrument or "").encode(),
            str(frag.run_number or 0).encode(),
            str(frag.lane or 0).encode(),
            str(frag.tile or 0).encode(),
            str(frag.xpos or 0).encode(),
            str(frag.ypos or 0).encode(),
            (frag.index_sequence or "0").encode(),
            str(frag.read or 1).encode(),
            frag.sequence.replace(b"N", b"."),
            qual,
            b"1" if frag.filter_passed in (None, True) else b"0",
        ]
        return b"\t".join(fields) + b"\n"

    def write(self, stream, batch: FragmentBatch) -> None:
        for frag in batch.fragments:
            stream.write(self.format_record(frag))
