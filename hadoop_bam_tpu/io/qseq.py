"""QSEQ input/output (QseqInputFormat.java / QseqOutputFormat.java).

11 tab-separated fields per line: machine, run, lane, tile, x, y, index,
read, sequence, quality, filter.  Key = ``machine:run:lane:tile:x:y:read``
(:344-363); ``.`` bases become ``N`` and the index field treats ``0`` as
null (:378-385); default input quality encoding is Illumina Phred+64,
converted to Sanger (:403-426).  Split resync = drop the partial first line
(:136-155).  The writer emits ``N``→``.`` and re-encodes quality
(QseqOutputFormat.java:98-157).
"""

from __future__ import annotations

from typing import List, Optional

from ..conf import (
    Configuration,
    INPUT_BASE_QUALITY_ENCODING,
    INPUT_FILTER_FAILED_QC,
    QSEQ_BASE_QUALITY_ENCODING,
    QSEQ_FILTER_FAILED_QC,
    QSEQ_OUTPUT_BASE_QUALITY_ENCODING,
)
from ..spec.fragment import (
    FormatException,
    FragmentBatch,
    SequencedFragment,
    convert_quality,
    verify_quality,
)
from .splits import ByteSplit
from .text import SplitLineReader, plan_byte_splits, read_decompressed

NUM_QSEQ_COLS = 11


def parse_qseq_line(line: bytes) -> tuple[str, SequencedFragment]:
    fields = line.split(b"\t")
    if len(fields) != NUM_QSEQ_COLS:
        raise FormatException(
            f"found {len(fields)} fields instead of 11. Line: {line!r}"
        )
    frag = SequencedFragment()
    frag.instrument = fields[0].decode()
    frag.run_number = int(fields[1])
    frag.lane = int(fields[2])
    frag.tile = int(fields[3])
    frag.xpos = int(fields[4])
    frag.ypos = int(fields[5])
    frag.read = int(fields[7])
    frag.filter_passed = fields[10][:1] != b"0"
    if fields[6][:1] == b"0":  # 0 is a null index sequence (:378-382)
        frag.index_sequence = None
    else:
        frag.index_sequence = fields[6].decode().replace(".", "N")
    frag.sequence = fields[8].replace(b".", b"N")
    frag.quality = bytes(fields[9])
    key = b":".join(fields[0:6] + [fields[7]]).decode()
    return key, frag


class QseqInputFormat:
    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()

    def _encoding(self) -> str:
        enc = self.conf.get(
            QSEQ_BASE_QUALITY_ENCODING,
            self.conf.get(INPUT_BASE_QUALITY_ENCODING, "illumina"),
        )
        if enc not in ("sanger", "illumina"):
            raise ValueError(f"Unknown input base quality encoding value {enc}")
        return enc

    def _filter_failed(self) -> bool:
        raw = self.conf.get(
            QSEQ_FILTER_FAILED_QC, self.conf.get(INPUT_FILTER_FAILED_QC)
        )
        c = Configuration({"k": raw} if raw is not None else None)
        return c.get_boolean("k", False)

    def get_splits(self, paths, split_size: int = 4 << 20) -> List[ByteSplit]:
        out: List[ByteSplit] = []
        for p in sorted(paths):
            out.extend(plan_byte_splits(p, split_size))
        return out

    def read_split(
        self, split: ByteSplit, data: Optional[bytes] = None
    ) -> FragmentBatch:
        if data is None:
            import os

            raw_size = os.path.getsize(split.path)
            data = read_decompressed(split.path)
            if len(data) != raw_size and split.start == 0:
                split = ByteSplit(split.path, 0, len(data))
        r = SplitLineReader(data, split.start, split.end)
        encoding = self._encoding()
        filter_failed = self._filter_failed()
        names: List[str] = []
        frags: List[SequencedFragment] = []
        for _, line in r.lines():
            if not line:
                continue
            key, frag = parse_qseq_line(line)
            if filter_failed and frag.filter_passed is False:
                continue
            if encoding == "illumina":
                frag.quality = convert_quality(frag.quality, "illumina", "sanger")
            else:
                bad = verify_quality(frag.quality, "sanger")
                if bad >= 0:
                    raise FormatException(
                        "qseq base quality score out of range for Sanger "
                        f"Phred+33 format (found {frag.quality[bad] - 33})."
                    )
            names.append(key)
            frags.append(frag)
        return FragmentBatch.from_fragments(names, frags)


class QseqOutputFormat:
    """Write fragments as QSEQ lines (QseqOutputFormat.java:98-157)."""

    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()
        enc = self.conf.get(QSEQ_OUTPUT_BASE_QUALITY_ENCODING, "illumina")
        if enc not in ("sanger", "illumina"):
            raise ValueError(f"Unknown output base quality encoding {enc}")
        self.encoding = enc

    def format_record(self, frag: SequencedFragment) -> bytes:
        qual = frag.quality
        if self.encoding == "illumina":
            qual = convert_quality(qual, "sanger", "illumina")
        fields = [
            (frag.instrument or "").encode(),
            str(frag.run_number or 0).encode(),
            str(frag.lane or 0).encode(),
            str(frag.tile or 0).encode(),
            str(frag.xpos or 0).encode(),
            str(frag.ypos or 0).encode(),
            (frag.index_sequence or "0").encode(),
            str(frag.read or 1).encode(),
            frag.sequence.replace(b"N", b"."),
            qual,
            b"1" if frag.filter_passed in (None, True) else b"0",
        ]
        return b"\t".join(fields) + b"\n"

    def write(self, stream, batch: FragmentBatch) -> None:
        for frag in batch.fragments:
            stream.write(self.format_record(frag))
