"""FASTQ input/output with the reference's resync, ID-parse, and quality
rules (FastqInputFormat.java, FastqOutputFormat.java).

- split resync: scan for an ``@`` line with a ``+`` line two lines later,
  backtracking when the guess was the quality line (:156-198),
- Casava 1.8 Illumina ID regex → metadata (:92-93, 362-381), ``/N``
  read-number suffix fallback (:349-360),
- qualities converted to Sanger (Illumina input) or range-verified
  (:318-341); failed-QC filtering per ``hbam.fastq-input.filter-failed-qc``,
- writer reconstructs the ID from metadata when present and re-encodes
  quality per ``hbam.fastq-output.base-quality-encoding``
  (FastqOutputFormat.java:117-183).
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

import numpy as np

from ..conf import (
    Configuration,
    FASTQ_BASE_QUALITY_ENCODING,
    FASTQ_FILTER_FAILED_QC,
    FASTQ_OUTPUT_BASE_QUALITY_ENCODING,
    INPUT_BASE_QUALITY_ENCODING,
    INPUT_FILTER_FAILED_QC,
)
from ..spec.fragment import (
    ILLUMINA_MAX,
    ILLUMINA_OFFSET,
    SANGER_MAX,
    SANGER_OFFSET,
    FormatException,
    FragmentBatch,
    SequencedFragment,
    convert_quality,
    verify_quality,
)
from .splits import ByteSplit
from .text import (
    SplitLineReader,
    decode_slices,
    gather_padded,
    line_table,
    plan_byte_splits,
)

# Casava 1.8: instrument:run:flowcell:lane:tile:x:y read:filtered:control:index
ILLUMINA_PATTERN = re.compile(
    r"([^:]+):(\d+):([^:]*):(\d+):(\d+):(-?\d+):(-?\d+)\s+([123]):([YN]):(\d+):(.*)"
)


def scan_illumina_id(name: str, frag: SequencedFragment) -> bool:
    m = ILLUMINA_PATTERN.fullmatch(name)
    if not m:
        return False
    frag.instrument = m.group(1)
    frag.run_number = int(m.group(2))
    frag.flowcell_id = m.group(3)
    frag.lane = int(m.group(4))
    frag.tile = int(m.group(5))
    frag.xpos = int(m.group(6))
    frag.ypos = int(m.group(7))
    frag.read = int(m.group(8))
    frag.filter_passed = m.group(9) == "N"
    frag.control_number = int(m.group(10))
    frag.index_sequence = m.group(11)
    return True


def scan_read_number(name: str, frag: SequencedFragment) -> None:
    """``/N`` suffix fallback (FastqInputFormat.java:349-360)."""
    if len(name) >= 2 and name[-2] == "/" and name[-1].isdigit():
        frag.read = int(name[-1])


def _fastq_materializer(qual_lens):
    """Lazy per-record view builder: replays the reference's stateful
    id-parse chain (Illumina regex until first failure, then ``/N``)."""

    def build(batch):
        out = []
        look_for_illumina = True
        for i, name in enumerate(batch.names):
            sl = int(batch.lengths[i])
            ql = int(qual_lens[i])
            frag = SequencedFragment(
                sequence=batch.seq[i, :sl].tobytes(),
                quality=batch.qual[i, :ql].tobytes(),
            )
            look_for_illumina = look_for_illumina and scan_illumina_id(
                name, frag
            )
            if not look_for_illumina:
                scan_read_number(name, frag)
            out.append(frag)
        return out

    return build


class FastqInputFormat:
    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()

    def _encoding(self) -> str:
        enc = self.conf.get(
            FASTQ_BASE_QUALITY_ENCODING,
            self.conf.get(INPUT_BASE_QUALITY_ENCODING, "sanger"),
        )
        if enc not in ("sanger", "illumina"):
            raise ValueError(f"Unknown input base quality encoding value {enc}")
        return enc

    def _filter_failed(self) -> bool:
        raw = self.conf.get(
            FASTQ_FILTER_FAILED_QC, self.conf.get(INPUT_FILTER_FAILED_QC)
        )
        c = Configuration({"k": raw} if raw is not None else None)
        return c.get_boolean("k", False)

    def get_splits(self, paths, split_size: int = 4 << 20) -> List[ByteSplit]:
        out: List[ByteSplit] = []
        for p in sorted(paths):
            out.extend(plan_byte_splits(p, split_size))
        return out

    def position_at_first_record(
        self, data: bytes, start: int, end: int
    ) -> int:
        """The @/+ resync with backtracking (FastqInputFormat.java:156-198),
        hardened to the split-guesser stance: a candidate ``@`` line is
        trusted only when it heads TWO consecutive verified records
        (``@``/``+`` markers plus equal seq/qual lengths, twice over) —
        a lone ``@``-plus-``+`` look-ahead mistakes a quality string
        beginning with ``@`` for a record start whenever the split lands
        mid-quality-line.  The second record is waived only when the
        data ends before it can complete."""
        if start == 0:
            return 0
        r = SplitLineReader(data, start, len(data))
        pos = r.tell()
        while pos < end:
            line_start = pos
            line = r.read_line()
            if line is None:
                return len(data)
            if line.startswith(b"@"):
                backtrack = r.tell()
                window = [line]
                for _ in range(7):
                    nxt = r.read_line()
                    if nxt is None:
                        break
                    window.append(nxt)

                def frame(i: int) -> Optional[bool]:
                    if i + 3 >= len(window):
                        return None  # incomplete: data ran out
                    return (
                        window[i].startswith(b"@")
                        and window[i + 2].startswith(b"+")
                        and len(window[i + 1]) == len(window[i + 3])
                    )

                first, second = frame(0), frame(4)
                if first and (second or second is None):
                    return line_start
                r.pos = backtrack  # not a record start: resume after it
                pos = backtrack
            else:
                pos = r.tell()
        return pos

    def read_split(
        self, split: ByteSplit, data: Optional[bytes] = None
    ) -> FragmentBatch:
        """Vectorized split read (SURVEY §7 stage 8): one newline scan
        builds the line table, one batched gather builds the padded SoA
        seq/qual tensors, quality verify/convert run as masked array ops.
        Per-record ``SequencedFragment`` objects materialize lazily, with
        the reference's stateful Illumina-then-``/N`` id-parse rule."""
        if data is None:
            # Split-local window read: O(split) bytes off the filesystem
            # (a FASTQ record spans 4 lines, so the window keeps 4 complete
            # lines past the split end); gzip falls back to the whole
            # (unsplittable) decompressed payload.
            from .text import read_split_window

            data, split = read_split_window(split, min_lines_past_end=4)
        start = self.position_at_first_record(data, split.start, split.end)
        encoding = self._encoding()
        filter_failed = self._filter_failed()

        a = np.frombuffer(data, dtype=np.uint8)
        # Keep lines up to the end of a record straddling the split end
        # (3 continuation lines at most) — but never scan to EOF: the scan
        # window is O(split), not O(file).
        from .text import MAX_LINE_LENGTH

        line_stop = min(len(a), split.end + 4 * (MAX_LINE_LENGTH + 1))
        starts, lens = line_table(a, start, line_stop)
        # Records = consecutive 4-line groups whose id line starts before
        # the split end (the read-past-end protocol finishes the tail).
        id_idx = np.arange(0, len(starts), 4)
        id_idx = id_idx[starts[id_idx] < split.end]
        n = len(id_idx)
        if n == 0:
            return FragmentBatch(
                seq=np.zeros((0, 0), np.uint8),
                qual=np.zeros((0, 0), np.uint8),
                lengths=np.zeros(0, np.int32),
                _names=[],
            )
        if id_idx[-1] + 3 >= len(starts):
            name = bytes(
                a[starts[id_idx[-1]] + 1 :][: 200]
            ).split(b"\n")[0].decode(errors="replace")
            raise FormatException(
                f"unexpected end of file in fastq record. Id: {name}"
            )
        bad_at = a[starts[id_idx]] != 0x40  # '@'
        if bad_at.any():
            k = int(id_idx[np.argmax(bad_at)])
            line = bytes(a[starts[k] : starts[k] + lens[k]])
            raise FormatException(
                f"unexpected fastq record start at {split.path}: {line!r}"
            )
        plus_idx = id_idx + 2
        bad_plus = (lens[plus_idx] < 1) | (a[starts[plus_idx]] != 0x2B)
        if bad_plus.any():
            j = int(np.argmax(bad_plus))
            k = int(plus_idx[j])
            line = bytes(a[starts[k] : starts[k] + lens[k]])
            name = bytes(
                a[starts[id_idx[j]] + 1 : starts[id_idx[j]] + lens[id_idx[j]]]
            ).decode()
            raise FormatException(
                "unexpected fastq line separating sequence and quality: "
                f"{line!r}. Sequence ID: {name}"
            )

        name_starts = starts[id_idx] + 1
        name_lens = lens[id_idx] - 1
        names: Optional[List[str]] = None  # decoded only when needed
        seq_lens = lens[id_idx + 1]
        qual_lens = lens[id_idx + 3]
        W = int(max(seq_lens.max(), qual_lens.max()))
        seq = gather_padded(a, starts[id_idx + 1], seq_lens, W)
        qual = gather_padded(a, starts[id_idx + 3], qual_lens, W)

        def qmask_of():
            return np.arange(W)[None, :] < qual_lens[:, None]

        if filter_failed:
            # filter-failed-qc needs the Casava filter flag — parse ids
            # with the same stateful rule the record loop used.
            names = decode_slices(a, name_starts, name_lens)
            keep = np.ones(n, dtype=bool)
            probing = True
            for i, nm in enumerate(names):
                if not probing:
                    break
                m = ILLUMINA_PATTERN.fullmatch(nm)
                if m is None:
                    probing = False
                elif m.group(9) == "Y":
                    keep[i] = False
            if not keep.all():
                sel = np.nonzero(keep)[0]
                names = [names[i] for i in sel]
                seq, qual = seq[sel], qual[sel]
                seq_lens, qual_lens = seq_lens[sel], qual_lens[sel]
                name_starts, name_lens = name_starts[sel], name_lens[sel]
                n = len(sel)

        if encoding == "illumina":
            qmask = qmask_of()
            q16 = qual.astype(np.int16)
            inr = (q16 >= ILLUMINA_OFFSET) & (
                q16 <= ILLUMINA_OFFSET + ILLUMINA_MAX
            )
            if bool((qmask & ~inr).any()):
                r, c = np.argwhere(qmask & ~inr)[0]
                raise FormatException(
                    "base quality score out of range for Illumina Phred+64 "
                    f"format (found {int(qual[r, c]) - ILLUMINA_OFFSET} but "
                    f"acceptable range is [0,{ILLUMINA_MAX}]).\n"
                    "Maybe qualities are encoded in Sanger format?\n"
                )
            qual = np.where(
                qmask, (q16 - (ILLUMINA_OFFSET - SANGER_OFFSET)), 0
            ).astype(np.uint8)
        else:
            # One-pass check: (q - 33) wraps below 33 in uint8, so a single
            # compare flags both bounds; padding zeros wrap too, so the
            # expected violation count is exactly the padding count.
            n_bad = int(
                np.count_nonzero((qual - SANGER_OFFSET) > SANGER_MAX)
            )
            n_pad = int(qual.shape[0] * qual.shape[1] - qual_lens.sum())
            if n_bad != n_pad:
                inr = (qual >= SANGER_OFFSET) & (
                    qual <= SANGER_OFFSET + SANGER_MAX
                )
                r, c = np.argwhere(qmask_of() & ~inr)[0]
                bad_name = str(
                    memoryview(a)[
                        int(name_starts[r]) : int(name_starts[r] + name_lens[r])
                    ],
                    "utf-8",
                )
                raise FormatException(
                    "fastq base quality score out of range for Sanger "
                    f"Phred+33 format (found {int(qual[r, c]) - 33}).\n"
                    "Although Sanger format has been requested, maybe "
                    "qualities are in Illumina Phred+64 format?\n"
                    f"Sequence ID: {bad_name}"
                )

        return FragmentBatch(
            seq=seq,
            qual=qual,
            lengths=seq_lens.astype(np.int32),
            _names=names,
            name_source=(a, name_starts, name_lens),
            materializer=_fastq_materializer(qual_lens.astype(np.int32)),
        )


class FastqOutputFormat:
    """Write fragments as FASTQ (FastqOutputFormat.java semantics)."""

    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()
        enc = self.conf.get(FASTQ_OUTPUT_BASE_QUALITY_ENCODING, "sanger")
        if enc not in ("sanger", "illumina"):
            raise ValueError(f"Unknown output base quality encoding {enc}")
        self.encoding = enc

    def format_record(
        self, frag: SequencedFragment, key: Optional[str] = None
    ) -> bytes:
        if frag.instrument is not None:
            # Reconstruct the Casava 1.8 id (FastqOutputFormat.java:117-145).
            name = (
                f"{frag.instrument}:{frag.run_number}:{frag.flowcell_id}:"
                f"{frag.lane}:{frag.tile}:{frag.xpos}:{frag.ypos} "
                f"{frag.read or 1}:"
                f"{'N' if frag.filter_passed in (None, True) else 'Y'}:"
                f"{frag.control_number or 0}:{frag.index_sequence or ''}"
            )
        elif key is not None:
            name = key
        else:
            name = ""
        qual = frag.quality
        if self.encoding == "illumina":
            qual = convert_quality(qual, "sanger", "illumina")
        return b"@" + name.encode() + b"\n" + frag.sequence + b"\n+\n" + qual + b"\n"

    def write(self, stream, batch: FragmentBatch) -> None:
        for name, frag in zip(batch.names, batch.fragments):
            stream.write(self.format_record(frag, key=name))
