"""FASTQ input/output with the reference's resync, ID-parse, and quality
rules (FastqInputFormat.java, FastqOutputFormat.java).

- split resync: scan for an ``@`` line with a ``+`` line two lines later,
  backtracking when the guess was the quality line (:156-198),
- Casava 1.8 Illumina ID regex → metadata (:92-93, 362-381), ``/N``
  read-number suffix fallback (:349-360),
- qualities converted to Sanger (Illumina input) or range-verified
  (:318-341); failed-QC filtering per ``hbam.fastq-input.filter-failed-qc``,
- writer reconstructs the ID from metadata when present and re-encodes
  quality per ``hbam.fastq-output.base-quality-encoding``
  (FastqOutputFormat.java:117-183).
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

from ..conf import (
    Configuration,
    FASTQ_BASE_QUALITY_ENCODING,
    FASTQ_FILTER_FAILED_QC,
    FASTQ_OUTPUT_BASE_QUALITY_ENCODING,
    INPUT_BASE_QUALITY_ENCODING,
    INPUT_FILTER_FAILED_QC,
)
from ..spec.fragment import (
    FormatException,
    FragmentBatch,
    SequencedFragment,
    convert_quality,
    verify_quality,
)
from .splits import ByteSplit
from .text import SplitLineReader, plan_byte_splits, read_decompressed

# Casava 1.8: instrument:run:flowcell:lane:tile:x:y read:filtered:control:index
ILLUMINA_PATTERN = re.compile(
    r"([^:]+):(\d+):([^:]*):(\d+):(\d+):(-?\d+):(-?\d+)\s+([123]):([YN]):(\d+):(.*)"
)


def scan_illumina_id(name: str, frag: SequencedFragment) -> bool:
    m = ILLUMINA_PATTERN.fullmatch(name)
    if not m:
        return False
    frag.instrument = m.group(1)
    frag.run_number = int(m.group(2))
    frag.flowcell_id = m.group(3)
    frag.lane = int(m.group(4))
    frag.tile = int(m.group(5))
    frag.xpos = int(m.group(6))
    frag.ypos = int(m.group(7))
    frag.read = int(m.group(8))
    frag.filter_passed = m.group(9) == "N"
    frag.control_number = int(m.group(10))
    frag.index_sequence = m.group(11)
    return True


def scan_read_number(name: str, frag: SequencedFragment) -> None:
    """``/N`` suffix fallback (FastqInputFormat.java:349-360)."""
    if len(name) >= 2 and name[-2] == "/" and name[-1].isdigit():
        frag.read = int(name[-1])


class FastqInputFormat:
    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()

    def _encoding(self) -> str:
        enc = self.conf.get(
            FASTQ_BASE_QUALITY_ENCODING,
            self.conf.get(INPUT_BASE_QUALITY_ENCODING, "sanger"),
        )
        if enc not in ("sanger", "illumina"):
            raise ValueError(f"Unknown input base quality encoding value {enc}")
        return enc

    def _filter_failed(self) -> bool:
        raw = self.conf.get(
            FASTQ_FILTER_FAILED_QC, self.conf.get(INPUT_FILTER_FAILED_QC)
        )
        c = Configuration({"k": raw} if raw is not None else None)
        return c.get_boolean("k", False)

    def get_splits(self, paths, split_size: int = 4 << 20) -> List[ByteSplit]:
        out: List[ByteSplit] = []
        for p in sorted(paths):
            out.extend(plan_byte_splits(p, split_size))
        return out

    def position_at_first_record(
        self, data: bytes, start: int, end: int
    ) -> int:
        """The @/+ resync with backtracking (FastqInputFormat.java:156-198)."""
        if start == 0:
            return 0
        r = SplitLineReader(data, start, len(data))
        pos = r.tell()
        while pos < end:
            line_start = pos
            line = r.read_line()
            if line is None:
                return len(data)
            if line.startswith(b"@"):
                backtrack = r.tell()
                r.read_line()  # sequence?
                third = r.read_line()  # '+' if line_start was a record start
                if third is not None and third.startswith(b"+"):
                    return line_start
                r.pos = backtrack  # it was a quality line: resume after it
                pos = backtrack
            else:
                pos = r.tell()
        return pos

    def read_split(
        self, split: ByteSplit, data: Optional[bytes] = None
    ) -> FragmentBatch:
        if data is None:
            import os

            raw_size = os.path.getsize(split.path)
            data = read_decompressed(split.path)
            if len(data) != raw_size and split.start == 0:
                # unsplittable compressed file: the single split covers the
                # whole decompressed payload
                split = ByteSplit(split.path, 0, len(data))
        start = self.position_at_first_record(data, split.start, split.end)
        r = SplitLineReader(data, 0, split.end)
        r.pos = start
        encoding = self._encoding()
        filter_failed = self._filter_failed()
        names: List[str] = []
        frags: List[SequencedFragment] = []
        look_for_illumina = True
        while r.pos < split.end:
            id_line = r.read_line()
            if id_line is None:
                break
            if not id_line.startswith(b"@"):
                raise FormatException(
                    f"unexpected fastq record start at {split.path}: {id_line!r}"
                )
            name = id_line[1:].decode()
            seq = r.read_line()
            plus = r.read_line()
            qual = r.read_line()
            if seq is None or plus is None or qual is None:
                raise FormatException(
                    f"unexpected end of file in fastq record. Id: {name}"
                )
            if not plus.startswith(b"+"):
                raise FormatException(
                    "unexpected fastq line separating sequence and quality: "
                    f"{plus!r}. Sequence ID: {name}"
                )
            frag = SequencedFragment(sequence=bytes(seq), quality=bytes(qual))
            look_for_illumina = look_for_illumina and scan_illumina_id(
                name, frag
            )
            if not look_for_illumina:
                scan_read_number(name, frag)
            if filter_failed and frag.filter_passed is False:
                continue
            if encoding == "illumina":
                frag.quality = convert_quality(
                    frag.quality, "illumina", "sanger"
                )
            else:
                bad = verify_quality(frag.quality, "sanger")
                if bad >= 0:
                    raise FormatException(
                        "fastq base quality score out of range for Sanger "
                        f"Phred+33 format (found {frag.quality[bad] - 33}).\n"
                        "Although Sanger format has been requested, maybe "
                        "qualities are in Illumina Phred+64 format?\n"
                        f"Sequence ID: {name}"
                    )
            names.append(name)
            frags.append(frag)
        return FragmentBatch.from_fragments(names, frags)


class FastqOutputFormat:
    """Write fragments as FASTQ (FastqOutputFormat.java semantics)."""

    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()
        enc = self.conf.get(FASTQ_OUTPUT_BASE_QUALITY_ENCODING, "sanger")
        if enc not in ("sanger", "illumina"):
            raise ValueError(f"Unknown output base quality encoding {enc}")
        self.encoding = enc

    def format_record(
        self, frag: SequencedFragment, key: Optional[str] = None
    ) -> bytes:
        if frag.instrument is not None:
            # Reconstruct the Casava 1.8 id (FastqOutputFormat.java:117-145).
            name = (
                f"{frag.instrument}:{frag.run_number}:{frag.flowcell_id}:"
                f"{frag.lane}:{frag.tile}:{frag.xpos}:{frag.ypos} "
                f"{frag.read or 1}:"
                f"{'N' if frag.filter_passed in (None, True) else 'Y'}:"
                f"{frag.control_number or 0}:{frag.index_sequence or ''}"
            )
        elif key is not None:
            name = key
        else:
            name = ""
        qual = frag.quality
        if self.encoding == "illumina":
            qual = convert_quality(qual, "sanger", "illumina")
        return b"@" + name.encode() + b"\n" + frag.sequence + b"\n+\n" + qual + b"\n"

    def write(self, stream, batch: FragmentBatch) -> None:
        for name, frag in zip(batch.names, batch.fragments):
            stream.write(self.format_record(frag, key=name))
