"""Vectorized SAM text parse: a whole split in array/native passes.

The reference reads SAM through htsjdk's per-line codec
(SAMRecordReader.java:108-146, :171-179); the previous implementation here
mirrored that shape — ``sam_line_to_record`` per line — which made SAM the
only text format without the batched treatment (FASTQ/QSEQ/VCF tokenize
whole splits at once).  This module parses every line of a split in one
pass and emits the *binary* record blob — byte-identical to running
``spec.sam.sam_line_to_record`` + ``encode()`` per line — so SAM text
feeds the same SoA decode → key → sort pipeline as BAM.

Two tokenizer tiers produce the same column table: a single native C scan
(``hbam_sam_scan``: line + field + tag-token tables and the core integers
in one memchr-paced pass) and a NumPy fallback (newline/tab ``nonzero``
scans + batched gathers, the VCF tokenizer recipe).  One shared finisher
turns the columns into the blob, itself tiered native-then-NumPy per
stage (CIGAR, tags, emit).

Anything the array passes cannot prove well-formed — short field counts,
non-integer cores, CHROMs outside the header, exotic tags, any non-ASCII
byte (the exact parser operates on decoded code points, so byte-level
equivalence only holds for ASCII) — returns ``None`` and the caller falls
back to the exact per-line parser, whose error messages are the contract
(same stance as the VCF tokenizer).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..spec import bam
from .text import gather_padded, line_table, MAX_LINE_LENGTH

# -- lookup tables -----------------------------------------------------------

_SEQ_LUT = np.full(256, 15, dtype=np.uint8)
for _i, _c in enumerate(bam.SEQ_DECODE):
    _SEQ_LUT[ord(_c)] = _i
    _SEQ_LUT[ord(_c.lower())] = _i

_CIGAR_LUT = np.full(256, 255, dtype=np.uint8)
for _i, _c in enumerate(bam.CIGAR_OPS):
    _CIGAR_LUT[ord(_c)] = _i
# Ops consuming reference bases (span for reg2bin): M D N = X
_CIGAR_REF = np.zeros(16, dtype=np.int64)
for _i, _c in enumerate(bam.CIGAR_OPS):
    if _c in "MDN=X":
        _CIGAR_REF[_i] = 1

_IS_DIGIT = np.zeros(256, dtype=bool)
_IS_DIGIT[48:58] = True

_INT_FIELDS = (1, 3, 4, 7, 8)  # flag, pos, mapq, pnext, tlen


def _parse_ints(a, starts, lens):
    """Vectorized decimal parse of byte slices.  Returns (vals int64, ok).

    Native tier: one threaded C pass (hbam_parse_i64); NumPy fallback
    below keeps the pure-Python install working."""
    n = len(starts)
    if n == 0:
        return np.empty(0, np.int64), True
    from .. import native

    if native.available():
        try:
            vals = native.parse_i64(a, starts, lens)
        except ValueError:
            return None, False
        return vals, True
    W = int(lens.max())
    if W == 0 or W > 11:  # empty field or > int32-class digits
        return None, False
    mat = gather_padded(a, starts, lens, W)
    col = np.arange(W, dtype=np.int64)[None, :]
    valid = col < lens[:, None]
    neg = mat[:, 0] == 0x2D  # '-'
    first_dig = neg.astype(np.int64)
    dig_mask = valid & (col >= first_dig[:, None])
    d = mat.astype(np.int64) - 48
    if (((d < 0) | (d > 9)) & dig_mask).any() or (lens <= first_dig).any():
        return None, False
    vals = np.zeros(n, dtype=np.int64)
    for c in range(W):
        live = dig_mask[:, c]
        vals = np.where(live, vals * 10 + d[:, c], vals)
    return np.where(neg, -vals, vals), True


def _reg2bin_np(beg, end):
    """Vectorized UCSC binning (spec.bam.reg2bin semantics)."""
    e = end - 1
    out = np.zeros(len(beg), dtype=np.int64)
    done = np.zeros(len(beg), dtype=bool)
    for shift, offset in ((14, 4681), (17, 585), (20, 73), (23, 9), (26, 1)):
        hit = ~done & ((beg >> shift) == (e >> shift))
        out[hit] = offset + (beg[hit] >> shift)
        done |= hit
    return out


def _ragged_copy(dst, dst_off, src_off, lens, a, chunk=1 << 22):
    """dst[dst_off[i]+j] = a[src_off[i]+j] for j < lens[i], chunked so the
    index temporaries stay cache-sized."""
    n = len(lens)
    if n == 0:
        return
    csum = np.concatenate(([0], np.cumsum(lens)))
    r0 = 0
    while r0 < n:
        r1 = int(np.searchsorted(csum, csum[r0] + chunk, side="left"))
        r1 = max(r0 + 1, min(n, r1))
        ln = lens[r0:r1]
        total = int(csum[r1] - csum[r0])
        if total:
            j = np.arange(total, dtype=np.int64) - np.repeat(
                csum[r0:r1] - csum[r0], ln
            )
            dst[np.repeat(dst_off[r0:r1], ln) + j] = a[
                np.repeat(src_off[r0:r1], ln) + j
            ]
        r0 = r1


def _scatter_u32(dst, at, vals):
    v = vals.astype(np.int64)
    for b in range(4):
        dst[at + b] = (v >> (8 * b)) & 0xFF


def _scatter_u16(dst, at, vals):
    v = vals.astype(np.int64)
    dst[at] = v & 0xFF
    dst[at + 1] = (v >> 8) & 0xFF


def _refid_lookup(a, starts, lens, header, allow_eq=False):
    """Vectorized reference-name → index via unique padded rows.

    Returns (refid int32[n], eq_mask, ok).  ``allow_eq`` treats '=' as a
    marker resolved by the caller (RNEXT).  Unknown names — or a hash
    collision between distinct names (verified by comparing every row
    against its bucket representative) — give ok=False and the exact path
    takes over."""
    n = len(starts)
    W = max(1, int(lens.max()) if n else 1)
    if W > 64:
        return None, None, False
    mat = gather_padded(a, starts, lens, W)
    Wp = -(-W // 8) * 8
    packed = np.zeros((n, Wp), np.uint8)
    packed[:, :W] = mat
    words = packed.view(np.uint64).reshape(n, Wp // 8)
    key = lens.astype(np.uint64).copy()
    for w in range(Wp // 8):
        key ^= words[:, w] * np.uint64(0x9E3779B97F4A7C15 + 2 * w + 1)
    uniq, first_idx, inv = np.unique(
        key, return_index=True, return_inverse=True
    )
    # The xor-mix is only a bucketing key: a collision would merge two
    # distinct names into one bucket.  Verify every row equals its bucket
    # representative byte-for-byte; any mismatch → exact path.
    if not (
        (mat == mat[first_idx][inv]).all()
        and (lens == lens[first_idx][inv]).all()
    ):
        return None, None, False
    names = []
    for i in first_idx:
        ln = int(lens[i])
        names.append(bytes(mat[i, :ln]).decode("ascii"))
    ids = np.empty(len(names), np.int64)
    eqs = np.zeros(len(names), bool)
    for k, nm in enumerate(names):
        if allow_eq and nm == "=":
            eqs[k] = True
            ids[k] = 0
            continue
        try:
            ids[k] = header.ref_index(nm)
        except KeyError:
            return None, None, False
    return ids[inv], eqs[inv], True


def _parse_cigars(a, starts, lens):
    """All CIGAR fields → (n_ops[n], op_values concat, span[n], ok).

    ``op_values`` are the BAM encoding ``len<<4 | op`` in record order;
    ``span`` sums reference-consuming op lengths (for reg2bin).  Native
    tier: two threaded C passes (count+validate, fill); NumPy fallback
    below."""
    from .. import native

    if native.available():
        try:
            n_ops, opvals, span, _ = native.parse_cigars(a, starts, lens)
        except ValueError:
            return None, None, None, False
        return n_ops, opvals.astype(np.int64), span, True
    n = len(starts)
    n_ops = np.zeros(n, dtype=np.int64)
    span = np.zeros(n, dtype=np.int64)
    star = (lens == 1) & (a[starts] == 0x2A)  # '*'
    act = ~star & (lens > 0)
    if (lens == 0).any():
        return None, None, None, False
    if not act.any():
        return n_ops, np.empty(0, np.int64), span, True
    # Concatenate the active cigar fields.
    c_lens = lens[act]
    M = int(c_lens.sum())
    concat = np.empty(M, dtype=np.uint8)
    csum = np.concatenate(([0], np.cumsum(c_lens)))
    _ragged_copy(concat, csum[:-1], starts[act], c_lens, a)
    rid = np.repeat(np.arange(len(c_lens)), c_lens)  # active-row id per char
    is_op = _CIGAR_LUT[concat] != 255
    is_dig = _IS_DIGIT[concat]
    if not (is_op | is_dig).all():
        return None, None, None, False
    # Last char of each field must be an op; field must start with a digit.
    if not is_op[csum[1:] - 1].all() or not is_dig[csum[:-1]].all():
        return None, None, None, False
    # A digit must follow every op except at field end.
    after_op = np.zeros(M, dtype=bool)
    after_op[1:] = is_op[:-1]
    after_op[csum[:-1]] = False  # field starts belong to this field
    if (after_op & ~is_dig).any():
        return None, None, None, False
    op_pos = np.nonzero(is_op)[0]
    G = len(op_pos)
    # Digit group = index of the op it precedes.
    grp = np.cumsum(is_op) - is_op
    dig_pos = np.nonzero(is_dig)[0]
    dgrp = grp[dig_pos]
    counts = np.bincount(dgrp, minlength=G)
    if (counts > 9).any():  # > 9 digits: let the exact path range-check
        return None, None, None, False
    gstart = np.concatenate(([0], np.cumsum(counts)))[:-1]
    idx_in_grp = np.arange(len(dig_pos)) - gstart[dgrp]
    weight = 10 ** (counts[dgrp] - 1 - idx_in_grp).astype(np.int64)
    vals = np.bincount(
        dgrp, weights=(concat[dig_pos] - 48).astype(np.int64) * weight,
        minlength=G,
    ).astype(np.int64)
    if (vals >= (1 << 28)).any():
        return None, None, None, False
    opc = _CIGAR_LUT[concat[op_pos]].astype(np.int64)
    op_rid = rid[op_pos]
    n_ops_act = np.bincount(op_rid, minlength=len(c_lens))
    n_ops[act] = n_ops_act
    span_act = np.bincount(
        op_rid, weights=vals * _CIGAR_REF[opc], minlength=len(c_lens)
    ).astype(np.int64)
    span[act] = span_act
    return n_ops, (vals << 4) | opc, span, True


_TAG_I_WIDTH_BOUNDS = (
    (1, -128, 127),        # c
    (1, 0, 255),           # C
    (2, -32768, 32767),    # s
    (2, 0, 65535),         # S
    (4, -(1 << 31), (1 << 31) - 1),  # i
    (4, 0, (1 << 32) - 1),  # I
)
_TAG_I_CODES = b"cCsSiI"


def _encode_tags(a, tok_start, tok_len, tok_rid, n_records):
    """Vectorized tag tokens → (tag_bytes_per_record, blob).

    Tokens are ``TAG:T:VALUE`` byte slices in row-major (record, position)
    order — exactly ``f[11:]`` order, already filtered to len >= 5 (the
    exact parser skips shorter tokens).  Native tier handles every type in
    C; the NumPy fallback vectorizes A/i/Z/H and per-token-encodes f/B.
    Returns None on anything the exact path should error on."""
    from ..spec.sam import _encode_tag
    from .. import native

    T = len(tok_start)
    if T == 0:
        return np.zeros(n_records, np.int64), np.empty(0, np.uint8)
    if native.available():
        try:
            enc_len, blob = native.encode_tags(a, tok_start, tok_len)
        except ValueError:
            return None
        rec_bytes = np.bincount(
            tok_rid, weights=enc_len, minlength=n_records
        ).astype(np.int64)
        return rec_bytes, blob
    typ = a[tok_start + 3]
    vstart = tok_start + 5
    vlen = tok_len - 5
    is_A = typ == ord("A")
    is_i = typ == ord("i")
    is_Z = (typ == ord("Z")) | (typ == ord("H"))
    is_other = ~(is_A | is_i | is_Z)

    enc_len = np.zeros(T, dtype=np.int64)
    enc_len[is_A] = 3 + np.minimum(vlen[is_A], 1)
    enc_len[is_Z] = 3 + vlen[is_Z] + 1

    ivals = None
    iwidth = None
    icode = None
    if is_i.any():
        ivals, ok = _parse_ints(a, vstart[is_i], vlen[is_i])
        if not ok:
            return None
        iwidth = np.zeros(len(ivals), dtype=np.int64)
        icode = np.zeros(len(ivals), dtype=np.uint8)
        done = np.zeros(len(ivals), dtype=bool)
        for k, (w, lo, hi) in enumerate(_TAG_I_WIDTH_BOUNDS):
            hit = ~done & (ivals >= lo) & (ivals <= hi)
            iwidth[hit] = w
            icode[hit] = _TAG_I_CODES[k]
            done |= hit
        if not done.all():
            return None  # out of u32 range: exact path raises SamError
        enc_len[is_i] = 3 + iwidth

    other_blobs = {}
    if is_other.any():
        # f/B (and any unknown type, which must raise via the exact
        # encoder): per-token host encode — rare types.
        oi = np.nonzero(is_other)[0]
        for t in oi:
            s, l = int(tok_start[t]), int(tok_len[t])
            tok = bytes(a[s : s + l]).decode("ascii")
            try:
                b = _encode_tag(tok[:2], tok[3], tok[5:])
            except Exception:
                return None
            other_blobs[int(t)] = np.frombuffer(b, np.uint8)
            enc_len[t] = len(b)

    dst = np.concatenate(([0], np.cumsum(enc_len)))[:-1]
    blob = np.zeros(int(enc_len.sum()), dtype=np.uint8)
    blob[dst] = a[tok_start]
    blob[dst + 1] = a[tok_start + 1]
    blob[dst + 2] = typ
    if is_A.any():
        has_v = is_A & (vlen > 0)
        blob[dst[has_v] + 3] = a[vstart[has_v]]
    if is_Z.any():
        _ragged_copy(blob, dst[is_Z] + 3, vstart[is_Z], vlen[is_Z], a)
        # NUL already zero-initialized.
    if ivals is not None and len(ivals):
        iv = ivals.astype(np.int64) & 0xFFFFFFFF  # two's complement
        d_i = dst[is_i]
        for b in range(4):
            m = iwidth > b
            blob[d_i[m] + 3 + b] = (iv[m] >> (8 * b)) & 0xFF
        blob[d_i + 2] = icode
    for t, ob in other_blobs.items():
        blob[dst[t] : dst[t] + len(ob)] = ob
    rec_bytes = np.bincount(
        tok_rid, weights=enc_len, minlength=n_records
    ).astype(np.int64)
    return rec_bytes, blob


# -- tokenizer tiers ---------------------------------------------------------
#
# Both produce the same column table ``sc``:
#   name_src/name_len (len 0 for '*'), rname_src/len, cigar_src/len,
#   rnext_src/len, seq_src/len, qual_src/len — int64[n]
#   ints — int64[n, 5] (flag, pos1, mapq, pnext1, tlen) or None (the NumPy
#     tier defers parsing to the finisher via int_src/int_len)
#   tok_start/tok_len/tok_rid — tag tokens, row-major, len >= 5 only


def _scan_native(a, lo: int, end: int) -> Optional[dict]:
    from .. import native

    window_end = min(len(a), end + 4 * (MAX_LINE_LENGTH + 1))
    try:
        return native.sam_scan(a, lo, end, window_end)
    except ValueError:
        return None


def _scan_numpy(a, lo: int, end: int) -> Optional[dict]:
    starts, lens = line_table(a, lo, end)
    if len(starts):
        keep = (lens > 0) & (a[np.minimum(starts, len(a) - 1)] != 0x40)
        starts, lens = starts[keep], lens[keep]
    n = len(starts)
    if n == 0:
        return {k: np.empty(0, np.int64) for k in (
            "name_src", "name_len", "rname_src", "rname_len", "cigar_src",
            "cigar_len", "rnext_src", "rnext_len", "seq_src", "seq_len",
            "qual_src", "qual_len", "tok_start", "tok_len", "tok_rid",
            "int_src", "int_len",
        )} | {"ints": None}
    line_end = starts + lens
    window_end = min(len(a), end + 4 * (MAX_LINE_LENGTH + 1))
    if window_end < len(a) and bool((line_end >= window_end).any()):
        return None  # line cut off by the bounded scan window

    # Field table: the k-th tab of line i.
    wlo, whi = int(starts[0]), int(line_end.max())
    tabs = wlo + np.nonzero(a[wlo:whi] == 0x09)[0]
    if len(tabs) == 0:
        return None
    t0 = np.searchsorted(tabs, starts)
    tk = t0[:, None] + np.arange(10)
    exists = tk < len(tabs)
    Tt = tabs[np.minimum(tk, len(tabs) - 1)]
    if not (exists & (Tt < line_end[:, None])).all():
        return None  # < 11 fields: exact error text needed
    fstart = np.concatenate([starts[:, None], Tt + 1], axis=1)  # [n, 11]
    tk10 = t0 + 10
    has_tags = (tk10 < len(tabs)) & (
        tabs[np.minimum(tk10, len(tabs) - 1)] < line_end
    )
    f10_end = np.where(
        has_tags, tabs[np.minimum(tk10, len(tabs) - 1)], line_end
    )
    fend = np.concatenate([Tt, f10_end[:, None]], axis=1)
    flen = fend - fstart

    qn_len = flen[:, 0].copy()
    qn_len[(qn_len == 1) & (a[fstart[:, 0]] == 0x2A)] = 0

    sc = {
        "name_src": fstart[:, 0], "name_len": qn_len,
        "rname_src": fstart[:, 2], "rname_len": flen[:, 2],
        "cigar_src": fstart[:, 5], "cigar_len": flen[:, 5],
        "rnext_src": fstart[:, 6], "rnext_len": flen[:, 6],
        "seq_src": fstart[:, 9], "seq_len": flen[:, 9],
        "qual_src": fstart[:, 10], "qual_len": flen[:, 10],
        "ints": None,
        "int_src": fstart[:, _INT_FIELDS],
        "int_len": flen[:, _INT_FIELDS],
    }

    # Tag tokens, row-major.
    tok_s_l, tok_e_l, tok_r_l = [], [], []
    if has_tags.any():
        t_hi = np.searchsorted(tabs, line_end)
        extra = t_hi - (t0 + 10)  # tag-separating tabs per line
        for k in range(int(extra.max())):
            live = has_tags & (extra >= k + 1)
            if not live.any():
                break
            ti = t0[live] + 10 + k
            s = tabs[ti] + 1
            nxt = ti + 1
            e = np.where(
                (nxt < len(tabs))
                & (tabs[np.minimum(nxt, len(tabs) - 1)] < line_end[live]),
                tabs[np.minimum(nxt, len(tabs) - 1)],
                line_end[live],
            )
            tok_s_l.append(s)
            tok_e_l.append(e)
            tok_r_l.append(np.nonzero(live)[0])
    if tok_s_l:
        tok_s = np.concatenate(tok_s_l)
        tok_e = np.concatenate(tok_e_l)
        tok_r = np.concatenate(tok_r_l)
        order = np.lexsort((tok_s, tok_r))
        tok_s, tok_e, tok_r = tok_s[order], tok_e[order], tok_r[order]
        keep = (tok_e - tok_s) >= 5
        sc["tok_start"] = tok_s[keep]
        sc["tok_len"] = (tok_e - tok_s)[keep]
        sc["tok_rid"] = tok_r[keep]
    else:
        sc["tok_start"] = np.empty(0, np.int64)
        sc["tok_len"] = np.empty(0, np.int64)
        sc["tok_rid"] = np.empty(0, np.int64)
    return sc


# -- the shared finisher -----------------------------------------------------


def _finish(a, sc: dict, header) -> Optional[np.ndarray]:
    """Column table → binary record blob (both tokenizer tiers feed this)."""
    n = len(sc["name_src"])
    if n == 0:
        return np.empty(0, np.uint8)
    if sc["ints"] is not None:
        ints = sc["ints"]
        flag, pos1, mapq, pnext1, tlen = (ints[:, c] for c in range(5))
    else:
        parsed = []
        for c in range(5):
            vals, ok = _parse_ints(a, sc["int_src"][:, c], sc["int_len"][:, c])
            if not ok:
                return None
            parsed.append(vals)
        flag, pos1, mapq, pnext1, tlen = parsed
    if (
        (flag < 0).any() or (flag > 0xFFFF).any()
        or (mapq < 0).any() or (mapq > 0xFF).any()
        or (np.abs(tlen) >= (1 << 31)).any()
        or (pos1 < 0).any() or (pnext1 < 0).any()
        or (pos1 > (1 << 31)).any() or (pnext1 > (1 << 31)).any()
    ):
        return None  # the exact path's struct.pack raises the real error

    refid, _, ok = _refid_lookup(a, sc["rname_src"], sc["rname_len"], header)
    if not ok:
        return None
    nrefid, eq_mask, ok = _refid_lookup(
        a, sc["rnext_src"], sc["rnext_len"], header, allow_eq=True
    )
    if not ok:
        return None
    nrefid = np.where(eq_mask, refid, nrefid)

    n_ops, op_vals, span, ok = _parse_cigars(
        a, sc["cigar_src"], sc["cigar_len"]
    )
    if not ok:
        return None
    if (n_ops > 0xFFFF).any():
        return None  # n_cigar_op overflows u16: exact path raises

    qn_len = sc["name_len"]
    if (qn_len + 1 > 255).any():
        return None  # exact path raises BamError("read name too long")
    seq_len = sc["seq_len"]
    seq_star = (seq_len == 1) & (a[sc["seq_src"]] == 0x2A)
    l_seq = np.where(seq_star, 0, seq_len)
    seq_bytes = (l_seq + 1) // 2
    qual_len = sc["qual_len"]
    # '*' OR empty: build_record's `qual if qual else 0xFF*l_seq` treats an
    # empty (zero-length) QUAL field exactly like '*'.
    qual_star = (
        (qual_len == 1) & (a[sc["qual_src"]] == 0x2A)
    ) | (qual_len == 0)
    qual_bytes = np.where(qual_star, l_seq, qual_len)

    res = _encode_tags(a, sc["tok_start"], sc["tok_len"], sc["tok_rid"], n)
    if res is None:
        return None
    tag_rec_bytes, tag_blob = res

    body_len = (
        32 + qn_len + 1 + 4 * n_ops + seq_bytes + qual_bytes + tag_rec_bytes
    )
    off = np.concatenate(([0], np.cumsum(body_len + 4)))
    total = int(off[-1])
    rec = off[:-1]
    pos0 = pos1 - 1
    npos0 = pnext1 - 1
    # bin: unmapped flag → span 1; else max(1, cigar span); pos<0 → 4680.
    eff_span = np.where((flag & bam.FLAG_UNMAPPED) != 0, 1,
                        np.maximum(1, span))
    bin_ = np.where(pos0 >= 0, _reg2bin_np(pos0, pos0 + eff_span), 4680)
    if (bin_ > 0xFFFF).any():
        return None  # bin overflows u16 (> ~1 Gbp positions): exact raises
    op_off = np.concatenate(([0], np.cumsum(n_ops)))[:-1]
    tag_at_rec = np.concatenate(([0], np.cumsum(tag_rec_bytes)))[:-1]

    from .. import native

    if native.available():
        try:
            return native.sam_emit(
                a, rec, body_len,
                (refid, pos0, mapq, bin_, n_ops, flag, l_seq, nrefid,
                 npos0, tlen),
                sc["name_src"], qn_len, op_off, op_vals,
                sc["seq_src"], seq_star,
                sc["qual_src"], qual_len, qual_star,
                tag_at_rec, tag_rec_bytes, tag_blob,
                total,
            )
        except ValueError:
            return None  # QUAL byte below '!': exact path raises

    # -- NumPy emit (no native library) ---------------------------------
    out = np.zeros(total, dtype=np.uint8)
    body = rec + 4
    _scatter_u32(out, rec, body_len)
    _scatter_u32(out, body + 0, refid & 0xFFFFFFFF)
    _scatter_u32(out, body + 4, pos0 & 0xFFFFFFFF)
    out[body + 8] = (qn_len + 1) & 0xFF
    out[body + 9] = mapq & 0xFF
    _scatter_u16(out, body + 10, bin_)
    _scatter_u16(out, body + 12, n_ops)
    _scatter_u16(out, body + 14, flag)
    _scatter_u32(out, body + 16, l_seq)
    _scatter_u32(out, body + 20, nrefid & 0xFFFFFFFF)
    _scatter_u32(out, body + 24, npos0 & 0xFFFFFFFF)
    _scatter_u32(out, body + 28, tlen & 0xFFFFFFFF)

    name_at = body + 32
    _ragged_copy(out, name_at, sc["name_src"], qn_len, a)
    cig_at = name_at + qn_len + 1
    if len(op_vals):
        op_rid = np.repeat(np.arange(n), n_ops)
        op_k = np.arange(len(op_vals)) - np.repeat(op_off, n_ops)
        _scatter_u32(out, cig_at[op_rid] + 4 * op_k, op_vals)
    seq_at = cig_at + 4 * n_ops
    act = ~seq_star & (l_seq > 0)
    if act.any():
        sb = seq_bytes[act]
        ssum = np.concatenate(([0], np.cumsum(sb)))
        tot = int(ssum[-1])
        j = np.arange(tot, dtype=np.int64) - np.repeat(ssum[:-1], sb)
        src0 = np.repeat(sc["seq_src"][act], sb) + 2 * j
        ls_r = np.repeat(l_seq[act], sb)
        hi_nib = _SEQ_LUT[a[src0]].astype(np.uint8)
        has_lo = 2 * j + 1 < ls_r
        lo_nib = np.where(
            has_lo, _SEQ_LUT[a[np.minimum(src0 + 1, len(a) - 1)]], 0
        ).astype(np.uint8)
        out[np.repeat(seq_at[act], sb) + j] = (hi_nib << 4) | lo_nib
    qual_at = seq_at + seq_bytes
    qs = qual_star & (l_seq > 0)
    if qs.any():
        # 0xFF fill for '*' quals (vectorized run fill)
        ln = l_seq[qs]
        csum = np.concatenate(([0], np.cumsum(ln)))
        j = np.arange(int(csum[-1]), dtype=np.int64) - np.repeat(
            csum[:-1], ln
        )
        out[np.repeat(qual_at[qs], ln) + j] = 0xFF
    qv = ~qual_star
    if qv.any():
        ln = qual_len[qv]
        csum = np.concatenate(([0], np.cumsum(ln)))
        tot = int(csum[-1])
        if tot:
            j = np.arange(tot, dtype=np.int64) - np.repeat(csum[:-1], ln)
            src = np.repeat(sc["qual_src"][qv], ln) + j
            vals = a[src].astype(np.int16) - 33
            if (vals < 0).any():
                return None
            out[np.repeat(qual_at[qv], ln) + j] = vals.astype(np.uint8)
    if len(tag_blob):
        tag_at = qual_at + qual_bytes
        ln = tag_rec_bytes
        csum = np.concatenate(([0], np.cumsum(ln)))
        j = np.arange(int(csum[-1]), dtype=np.int64) - np.repeat(
            csum[:-1], ln
        )
        out[np.repeat(tag_at, ln) + j] = tag_blob
    return out


def parse_split_vectorized(
    data, start: int, end: int, header
) -> Optional[np.ndarray]:
    """Parse every SAM line starting in ``[start, end)`` into the binary
    record blob (uint8 array), or ``None`` when any line needs the exact
    per-line parser.  Byte-identical to concatenating
    ``sam_line_to_record(line).encode()`` over the same lines."""
    a = data if isinstance(data, np.ndarray) else np.frombuffer(data, np.uint8)
    lo = start
    window_end = min(len(a), end + 4 * (MAX_LINE_LENGTH + 1))
    if lo > 0:
        # Split resync (SplitLineReader semantics), searched inside the
        # bounded window only — a resync point beyond it means a giant
        # line, which the exact path handles.
        w = np.flatnonzero(a[lo - 1 : window_end] == 0x0A)
        if len(w) == 0:
            return np.empty(0, np.uint8) if window_end == len(a) else None
        lo = lo - 1 + int(w[0]) + 1
        if lo >= end:
            return np.empty(0, np.uint8)
    # The exact parser operates on decoded code points; byte-level
    # equivalence holds only for pure-ASCII content (a non-ASCII SEQ
    # changes l_seq, invalid UTF-8 must raise).  One cheap screen over the
    # scan window sends anything non-ASCII to the exact path.
    if len(a) and bool((a[lo:window_end] >= 0x80).any()):
        return None
    from .. import native

    sc = _scan_native(a, lo, end) if native.available() else _scan_numpy(
        a, lo, end
    )
    if sc is None:
        return None
    return _finish(a, sc, header)
