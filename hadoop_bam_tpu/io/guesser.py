"""Heuristic record-start guessing inside arbitrary byte ranges.

The reference's BAMSplitGuesser finds a BAM record start within ``[beg, end)``
of a BGZF file by (1) scanning for candidate BGZF block headers in the first
64KiB, (2) byte-wise scanning each block's payload for a plausible record
start using field sanity rules, and (3) verifying by trial-decoding three
whole blocks of records (BAMSplitGuesser.java:108-339).

This implementation keeps the same three phases and the same acceptance rules
but restructures them batch-first: the window is buffered once, candidate
blocks are found with the native scanner, each block's payload is inflated
once, and the sanity rules run as NumPy boolean algebra over *all* offsets of
the payload at once instead of a byte-at-a-time loop — the SURVEY.md §7
stage-2 "vectorized scan" design.
"""

from __future__ import annotations

import struct
from typing import Optional

import numpy as np

from .. import native
from ..spec import bam, bgzf

# Buffer bound per guess: 3 blocks + one max payload - 2
# (BAMSplitGuesser.java:66-73).
MAX_BYTES_READ = 3 * 0xFFFF + 0xFFFE
BLOCKS_NEEDED_FOR_GUESS = 3
# block_size + fixed fields + 1-char NUL name + no cigar/seq.
SHORTEST_POSSIBLE_BAM_RECORD = 4 * 9 + 1 + 1


class BamSplitGuesser:
    """Find the first real BAM record start in ``[beg, end)`` of a file."""

    def __init__(self, data: bytes, n_refs: int):
        """``data``: the whole BGZF file (or enough of it); ``n_refs``: the
        reference-sequence count from the header, used in the sanity range
        checks (BAMSplitGuesser.java:99-100)."""
        self.data = data
        self.n_refs = n_refs

    def guess_next_record_start(self, beg: int, end: int) -> int:
        """Virtual offset of the first verifiable record in ``[beg, end)``;
        returns ``end`` (as a *file* offset sentinel, like the reference) when
        none is found (BAMSplitGuesser.java:106-110)."""
        if beg == 0:
            # Skip the header with a real reader — it can exceed the window
            # (BAMSplitGuesser.java:115-123, the 100MB-header regression).
            # Malformed data falls through to the scan, which then reports
            # the clean "no record found" sentinel.
            try:
                r = bgzf.BgzfReader(self.data)
                bam.read_header_stream(r)
                return r.tell_voffset()
            except (bgzf.BgzfError, bam.BamError, struct.error):
                pass

        # The buffer extends MAX_BYTES_READ past beg regardless of ``end``:
        # ``end`` bounds where a record may *start*, not the verify window
        # (BAMSplitGuesser.java:127-140 reads the full buffer; only the
        # candidate-block search is clamped to min(end-beg, 0xffff)).
        window = self.data[beg : min(beg + MAX_BYTES_READ, len(self.data))]
        first_bgzf_end = min(end - beg, 0xFFFF)
        cp = 0
        while True:
            cp = native.find_next_block(window, cp, first_bgzf_end)
            if cp < 0:
                return end
            up = self._guess_in_block(window, cp)
            if up is not None:
                return ((beg + cp) << 16) | up
            cp += 1

    # -- phase 2: vectorized candidate scan ---------------------------------

    def _candidate_offsets(self, payload: np.ndarray) -> np.ndarray:
        """All offsets in one block's payload passing the reference's sanity
        rules (BAMSplitGuesser.java:243-336), evaluated vectorized."""
        n = len(payload)
        limit = n - (SHORTEST_POSSIBLE_BAM_RECORD - 4)
        if limit <= 4:
            return np.empty(0, dtype=np.int64)

        # Candidate positions up ∈ [4, limit): the scan starts at offset 4
        # (BAMSplitGuesser.java:239-241) and checks fields *relative to the
        # record start* up-4.  Work in terms of s = up - 4 (record start).
        count = limit - 4
        s = np.arange(count, dtype=np.int64)  # record starts
        pad = np.zeros(40, dtype=np.uint8)  # allow vector reads near the end
        a = np.concatenate([payload, pad])

        def i32(off: int, cnt: int) -> np.ndarray:
            # little-endian signed i32 at record-relative offset `off` for
            # every candidate start
            return (
                a[off : off + cnt].astype(np.uint32)
                | (a[off + 1 : off + cnt + 1].astype(np.uint32) << 8)
                | (a[off + 2 : off + cnt + 2].astype(np.uint32) << 16)
                | (a[off + 3 : off + cnt + 3].astype(np.uint32) << 24)
            ).astype(np.int32)

        refid = i32(4, count)
        pos = i32(8, count)
        ok = (refid >= -1) & (refid <= self.n_refs) & (pos >= -1)

        nrefid = i32(24, count)
        npos = i32(28, count)
        ok &= (nrefid >= -1) & (nrefid <= self.n_refs) & (npos >= -1)

        name_len = a[12 : 12 + count].astype(np.int64)
        ok &= name_len >= 1
        nul_pos = s + 36 + name_len - 1
        # The NUL must sit inside this block's payload
        # (BAMSplitGuesser.java:296-301).
        ok &= nul_pos < n
        ok &= a[np.minimum(nul_pos, n - 1)] == 0

        n_cigar = (
            a[16 : 16 + count].astype(np.int64)
            | (a[17 : 17 + count].astype(np.int64) << 8)
        )
        l_seq = i32(20, count).astype(np.int64)
        zero_min = 32 + name_len + 4 * n_cigar + l_seq + (l_seq + 1) // 2
        block_size = i32(0, count).astype(np.int64)
        ok &= block_size >= zero_min

        return s[ok] + 4  # back to "up" space (offset of refID field)

    def _guess_in_block(self, window: bytes, cp: int) -> Optional[int]:
        try:
            payload, _ = bgzf.inflate_block(window, cp)
        except bgzf.BgzfError:
            return None
        cands = self._candidate_offsets(np.frombuffer(payload, dtype=np.uint8))
        for up in cands:
            up0 = int(up) - 4  # record start (block_size word)
            if self._verify(window, cp, up0):
                return up0
        return None

    # -- phase 3: trial decode of 3 blocks ----------------------------------

    def _verify(self, window: bytes, cp: int, up0: int) -> bool:
        """Decode records from (cp, up0) until BLOCKS_NEEDED_FOR_GUESS block
        boundaries were crossed (BAMSplitGuesser.java:177-231).  Running out
        of buffered data mid-record is acceptable iff ≥1 record decoded."""
        # Inflate up to BLOCKS_NEEDED_FOR_GUESS+1 consecutive blocks from cp.
        co, cs, us = [], [], []
        pos = cp
        while len(co) < BLOCKS_NEEDED_FOR_GUESS + 1 and pos < len(window):
            try:
                csize, usize = bgzf.read_block_at(window, pos)
            except bgzf.BgzfError:
                break  # chain ends, truncates, or lies inside the window
            co.append(pos)
            cs.append(csize)
            us.append(usize)
            pos += csize
        if not co:
            return False
        try:
            out, offs = native.inflate_blocks(
                window,
                np.asarray(co, dtype=np.int64),
                np.asarray(cs, dtype=np.int32),
                np.asarray(us, dtype=np.int32),
            )
        except bgzf.BgzfError:
            return False
        data = out.tobytes()
        block_starts = [int(x) for x in offs[:-1]]
        truncated = pos < len(window)  # more blocks exist beyond the buffer

        p = up0
        blocks_crossed = 0
        decoded_any = False
        while blocks_crossed < BLOCKS_NEEDED_FOR_GUESS:
            if p + 4 > len(data):
                break
            (bs,) = struct.unpack_from("<I", data, p)
            if p + 4 + bs > len(data):
                # Partial record at the end of the buffered window: EOF is
                # legitimate iff we already decoded something
                # (BAMSplitGuesser.java:218-230).
                return decoded_any and truncated
            if not self._sane_record(data, p, bs):
                return False
            decoded_any = True
            new_p = p + 4 + bs
            # Count crossed block boundaries like the reference's
            # getFilePointer tracking (:195-201).
            for b in block_starts:
                if p < b <= new_p:
                    blocks_crossed += 1
            p = new_p
            if p >= len(data) and blocks_crossed < BLOCKS_NEEDED_FOR_GUESS:
                # Clean EOF at a record boundary: codec returns null → accept
                # if anything decoded (BAMSplitGuesser.java:186-212).
                return decoded_any
        return decoded_any

    def _sane_record(self, data: bytes, p: int, bs: int) -> bool:
        """The eager-decode stand-in: strict field validation equivalent to
        ``record.setHeaderStrict`` + ``eagerDecode``
        (BAMSplitGuesser.java:190-193)."""
        if bs < 32:
            return False
        body = memoryview(data)[p + 4 : p + 4 + bs]
        refid, pos_ = struct.unpack_from("<ii", body, 0)
        name_len = body[8]
        n_cigar = struct.unpack_from("<H", body, 12)[0]
        l_seq = struct.unpack_from("<I", body, 16)[0]
        nrefid, npos = struct.unpack_from("<ii", body, 20)
        # setHeaderStrict resolves refIDs against the real header: strict
        # upper bound, unlike the scan's lenient `<= n_refs`.
        if not (-1 <= refid < self.n_refs) or not (-1 <= nrefid < self.n_refs):
            return False
        if pos_ < -1 or npos < -1:
            return False
        if name_len < 1:
            return False
        need = 32 + name_len + 4 * n_cigar + (l_seq + 1) // 2 + l_seq
        if bs < need:
            return False
        if body[32 + name_len - 1] != 0:
            return False
        # eagerDecode validates CIGAR operator codes (0..8).
        for k in range(n_cigar):
            (c,) = struct.unpack_from("<I", body, 32 + name_len + 4 * k)
            if (c & 0xF) > 8:
                return False
        return True


def find_record_start_in_payload(
    payload,
    n_refs: int,
    start: int = 0,
    verify_records: int = 4,
) -> Optional[int]:
    """First verifiable BAM record start at/after ``start`` in an
    *inflated* payload stream — the salvage-mode record-chain re-sync.

    After a quarantined BGZF member breaks the record chain, the next
    good segment begins at an unknown point inside a record.  This runs
    the guesser's phase-2 sanity rules (vectorized) over the payload and
    verifies each candidate by walking the chain with the strict phase-3
    per-record validation for up to ``verify_records`` records (a record
    truncated by the end of the payload is acceptable, like the
    reference's buffered-window EOF rule).  Returns the payload offset of
    the record's block_size word, or None.
    """
    arr = (
        payload
        if isinstance(payload, np.ndarray)
        else np.frombuffer(payload, dtype=np.uint8)
    )
    if start:
        arr = arr[start:]
    if len(arr) < SHORTEST_POSSIBLE_BAM_RECORD:
        return None
    g = BamSplitGuesser(b"", n_refs)
    data = arr.tobytes()
    n = len(data)
    for up in g._candidate_offsets(arr):
        p = int(up) - 4
        ok = True
        decoded = 0
        while decoded < verify_records and p + 4 <= n:
            (bs,) = struct.unpack_from("<I", data, p)
            if p + 4 + bs > n:
                break  # truncated tail: fine iff something decoded
            if not g._sane_record(data, p, bs):
                ok = False
                break
            decoded += 1
            p += 4 + bs
        if ok and decoded:
            return start + int(up) - 4
    return None


def guess_bgzf_block_start(data: bytes, beg: int, end: int) -> Optional[int]:
    """The plain-BGZF guesser (util/BGZFSplitGuesser.java:64-112): next
    verifiable block start in ``[beg, end)``, verified by actually inflating
    the candidate block with CRC checking."""
    window_end = min(len(data), end + 2 * 0xFFFF - 1)
    pos = beg
    while True:
        pos = native.find_next_block(data, pos, min(end, window_end))
        if pos < 0 or pos >= end:
            return None
        try:
            bgzf.inflate_block(data, pos, check_crc=True)
            return pos
        except bgzf.BgzfError:
            pos += 1
