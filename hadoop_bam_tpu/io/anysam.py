"""AnySAM dispatch: extension trust + first-byte content sniffing.

Reference semantics (AnySAMInputFormat.java / SAMFormat.java):
- with ``hadoopbam.anysam.trust-exts`` (default true), `.bam`/`.cram`/`.sam`
  extensions decide (SAMFormat.inferFromFilePath),
- otherwise the first byte: ``0x1f`` (gzip/BGZF) → BAM, ``C`` (CRAM magic)
  → CRAM, ``@`` (header line) → SAM (SAMFormat.java:53-62),
- per-path format decisions are cached (AnySAMInputFormat.java:126-156),
- getSplits partitions by format and delegates to the per-format planners
  (:223-256).

Output side: ``AnySamOutputFormat`` picks the writer from
``hadoopbam.anysam.output-format`` (AnySAMOutputFormat.java:32-58).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Union

from ..conf import ANYSAM_TRUST_EXTS, Configuration
from .bam import BamInputFormat, RecordBatch
from .sam import SamInputFormat
from .splits import ByteSplit, FileVirtualSplit

AnySplit = Union[ByteSplit, FileVirtualSplit]


def infer_from_file_path(path: str) -> Optional[str]:
    low = path.lower()
    if low.endswith(".bam"):
        return "bam"
    if low.endswith(".cram"):
        return "cram"
    if low.endswith(".sam"):
        return "sam"
    return None


def infer_from_data(first_byte: int) -> Optional[str]:
    """SAMFormat.inferFromData (SAMFormat.java:53-62)."""
    if first_byte == 0x1F:
        return "bam"
    if first_byte == 0x43:  # 'C' of the CRAM magic
        return "cram"
    if first_byte == 0x40:  # '@' of a header line
        return "sam"
    return None


class AnySamInputFormat:
    def __init__(self, conf: Optional[Configuration] = None):
        self.conf = conf or Configuration()
        self._format_cache: Dict[str, Optional[str]] = {}
        self._bam = BamInputFormat(self.conf)
        self._sam = SamInputFormat(self.conf)

    def get_format(self, path: str) -> str:
        if path in self._format_cache:
            fmt = self._format_cache[path]
        else:
            fmt = None
            if self.conf.get_boolean(ANYSAM_TRUST_EXTS, True):
                fmt = infer_from_file_path(path)
            if fmt is None:
                with open(path, "rb") as f:
                    head = f.read(1)
                fmt = infer_from_data(head[0]) if head else None
            self._format_cache[path] = fmt
        if fmt is None:
            raise IOError(f"unknown SAM format in {path}")
        return fmt

    def get_splits(self, paths, split_size: int = 4 << 20) -> List[AnySplit]:
        by_fmt: Dict[str, List[str]] = {}
        for p in paths:
            by_fmt.setdefault(self.get_format(p), []).append(p)
        out: List[AnySplit] = []
        for fmt, group in sorted(by_fmt.items()):
            if fmt == "bam":
                out.extend(self._bam.get_splits(group, split_size))
            elif fmt == "sam":
                out.extend(self._sam.get_splits(group, split_size))
            else:
                out.extend(self._cram().get_splits(group, split_size))
        return out

    def _cram(self):
        """One cached CRAM reader — its ReferenceSource parses the FASTA
        once, not per split."""
        if getattr(self, "_cram_fmt", None) is None:
            from .cram import CramInputFormat

            self._cram_fmt = CramInputFormat(self.conf)
        return self._cram_fmt

    def read_split(self, split: AnySplit, **kw) -> RecordBatch:
        """Per-format dispatch with the DeviceStream read-drive kwargs
        (``fields``/``with_keys``/``errors``/``stream``/...) passed
        through, so an AnySam format drops into
        ``DeviceStream.read_splits`` exactly like a BamInputFormat —
        the seam that lets ``pipeline.sort_bam`` take ``.cram`` input."""
        if isinstance(split, FileVirtualSplit):
            return self._bam.read_split(split, **kw)
        fmt = self.get_format(split.path)
        if fmt == "sam":
            # The text reader has no codec tiers or projection.
            return self._sam.read_split(split, data=kw.get("data"))
        return self._cram().read_split(split, **kw)

    def read_header(self, path: str):
        """Header via the per-format reader (BAM/SAM via
        ``io.bam.read_header``'s sniffing twin, CRAM via the file-header
        container)."""
        if self.get_format(path) == "cram":
            from .cram import read_cram_header

            return read_cram_header(path)
        from .bam import read_header

        return read_header(path)
