"""Sorted spill runs for the bounded-memory (out-of-core) coordinate sort.

The reference never materializes a file: records stream through an iterator
(BAMRecordReader.java:223-232) and Hadoop's shuffle spills sorted segments
to local disk before the reduce-side merge.  This module is the TPU build's
spill layer (SURVEY §7 hard part #3):

- **Run** — one sorted chunk spilled to disk: the raw record stream
  (size-word + body per record, already in key order) plus two memmappable
  sidebands, the sorted ``int64`` keys and the ``int64`` record byte
  offsets.  Slicing a key range out of a run is two ``searchsorted`` calls
  on the memmapped keys plus one contiguous disk read — no inflate, no
  record walk.
- **plan_ranges** — exact global key-range partitioning over a set of
  sorted runs such that every range's record-byte total fits a budget.
  Because every run is sorted, range sizes are computed *exactly* (no
  sampling skew) by binary-searching the 64-bit key space with
  ``searchsorted`` sums over the memmapped key arrays; a tie bigger than
  the budget degrades to an in-tie index split that preserves run order
  (and therefore overall stability).

The merge phase concatenates per-run slices in run order and stable-sorts,
which reproduces exactly the single-pass stable sort's output order.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

RUN_DATA_EXT = ".run"
RUN_KEYS_EXT = ".run.keys.npy"
RUN_OFFS_EXT = ".run.offs.npy"
RUN_IDX_EXT = ".run.idx.npy"
MANIFEST_NAME = "manifest.json"
_MANIFEST_VERSION = 1


def run_paths(directory: str, idx: int) -> Tuple[str, str, str, str]:
    base = os.path.join(directory, f"run-{idx:05d}")
    return (
        base + RUN_DATA_EXT,
        base + RUN_KEYS_EXT,
        base + RUN_OFFS_EXT,
        base + RUN_IDX_EXT,
    )


def write_run(
    directory: str,
    idx: int,
    batch,
    perm: np.ndarray,
    orig_idx: Optional[np.ndarray] = None,
) -> None:
    """Spill a sorted chunk: permuted raw record stream + key/offset sidebands.

    ``batch`` is a RecordBatch (or anything with ``.data``, ``.keys`` and
    ``soa['rec_off']/['rec_len']``); ``perm`` is the sort permutation.
    Writes are atomic (tmp + rename) so a crashed spill never leaves a
    half-run behind.

    ``orig_idx`` (int64, batch order) adds a third memmappable sideband:
    each spilled record's global read-order index, permuted like the
    keys.  The dedup fusion stage needs it — its duplicate mask is built
    in read order over the whole job, and the range-merge writes must map
    every range row back to that mask.  Omitted (the default) the run
    format is unchanged.
    """
    from .bam import gather_record_array

    data_p, keys_p, offs_p, idx_p = run_paths(directory, idx)
    stream = gather_record_array(batch, perm)
    keys_sorted = np.ascontiguousarray(batch.keys[perm], dtype=np.int64)
    lens = batch.soa["rec_len"].astype(np.int64)[perm] + 4
    offs = np.empty(len(lens) + 1, dtype=np.int64)
    offs[0] = 0
    np.cumsum(lens, out=offs[1:])
    targets = [
        (data_p, lambda f: f.write(stream.tobytes())),
        (keys_p, lambda f: np.save(f, keys_sorted)),
        (offs_p, lambda f: np.save(f, offs)),
    ]
    if orig_idx is not None:
        idx_sorted = np.ascontiguousarray(
            np.asarray(orig_idx, dtype=np.int64)[perm]
        )
        targets.append((idx_p, lambda f: np.save(f, idx_sorted)))
    for path, writer in targets:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            writer(f)
        os.replace(tmp, path)


@dataclass
class Run:
    """A spilled sorted run.

    Key/offset sidebands are memmapped (binary searches touch O(log n)
    pages); the record stream is read with ``pread`` into fresh buffers so
    spilled bytes never stay mapped into the process — peak RSS tracks the
    working set, not the spill size.
    """

    data_path: str
    keys: np.ndarray  # int64, sorted (memmap)
    offs: np.ndarray  # int64, len n+1, byte offset of each record (memmap)
    orig_idx: Optional[np.ndarray] = None  # int64, read-order index (memmap)

    @classmethod
    def open(cls, directory: str, idx: int) -> "Run":
        data_p, keys_p, offs_p, idx_p = run_paths(directory, idx)
        keys = np.load(keys_p, mmap_mode="r")
        offs = np.load(offs_p, mmap_mode="r")
        orig = (
            np.load(idx_p, mmap_mode="r") if os.path.exists(idx_p) else None
        )
        return cls(data_path=data_p, keys=keys, offs=offs, orig_idx=orig)

    @property
    def n(self) -> int:
        return len(self.keys)

    def bytes_between(self, i0: int, i1: int) -> int:
        return int(self.offs[i1]) - int(self.offs[i0])

    def slice_stream(self, i0: int, i1: int) -> np.ndarray:
        """Raw bytes of records [i0, i1) — one contiguous pread."""
        start = int(self.offs[i0])
        size = int(self.offs[i1]) - start
        if size == 0:
            return np.empty(0, dtype=np.uint8)
        out = np.empty(size, dtype=np.uint8)
        with open(self.data_path, "rb") as f:
            f.seek(start)
            got = f.readinto(memoryview(out))
        if got != size:
            raise IOError(
                f"short read from spill run {self.data_path}: "
                f"{got} of {size} bytes at {start}"
            )
        return out


def input_identity(paths: Sequence[str]) -> List[Dict]:
    """File-identity fingerprints of the job inputs — ``(path, size,
    mtime_ns)``, the same identity key the serve cache uses.  A resumed
    sort must refuse checkpoints written against different bytes."""
    out: List[Dict] = []
    for p in paths:
        st = os.stat(p)
        out.append(
            {"path": p, "size": st.st_size, "mtime_ns": st.st_mtime_ns}
        )
    return out


def write_manifest(
    spill_dir: str,
    inputs: List[Dict],
    n_records: int,
    run_count: int,
    memory_budget: int,
    mark_duplicates: bool,
    sort_order: str = "coordinate",
) -> None:
    """Checkpoint the completed spill phase: inputs identity, job shape,
    and the byte size of every run sideband.  Written atomically *after*
    phase 1 finishes, so its existence certifies every run file it names
    (a ``kill -9`` mid-spill leaves no manifest → the rerun redoes phase 1
    from scratch; a kill mid-*merge* finds a valid manifest and reuses the
    runs as checkpoints)."""
    runs = []
    for k in range(run_count):
        data_p, keys_p, offs_p, idx_p = run_paths(spill_dir, k)
        entry = {
            "data": os.path.getsize(data_p),
            "keys": os.path.getsize(keys_p),
            "offs": os.path.getsize(offs_p),
        }
        if os.path.exists(idx_p):
            entry["idx"] = os.path.getsize(idx_p)
        runs.append(entry)
    doc = {
        "version": _MANIFEST_VERSION,
        "inputs": inputs,
        "n_records": n_records,
        "run_count": run_count,
        "memory_budget": memory_budget,
        "mark_duplicates": mark_duplicates,
        "sort_order": sort_order,
        "runs": runs,
    }
    path = os.path.join(spill_dir, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def load_manifest(
    spill_dir: str,
    inputs: List[Dict],
    memory_budget: int,
    mark_duplicates: bool,
    sort_order: str = "coordinate",
) -> Optional[Dict]:
    """The validated checkpoint, or None (missing / stale / mismatched).

    Validation is conservative: same format version, same input identity
    (path+size+mtime_ns), same budget, markdup setting and sort order
    (all three change the spill plan — a coordinate checkpoint must
    never seed a queryname rerun), and every named run file present at
    its recorded size.  Anything off → redo phase 1; a checkpoint is an
    optimization, never a correctness dependency."""
    path = os.path.join(spill_dir, MANIFEST_NAME)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if (
        doc.get("version") != _MANIFEST_VERSION
        or doc.get("inputs") != inputs
        or doc.get("memory_budget") != memory_budget
        or bool(doc.get("mark_duplicates")) != bool(mark_duplicates)
        or doc.get("sort_order", "coordinate") != sort_order
        or doc.get("run_count") != len(doc.get("runs", []))
    ):
        return None
    for k, entry in enumerate(doc["runs"]):
        data_p, keys_p, offs_p, idx_p = run_paths(spill_dir, k)
        try:
            if (
                os.path.getsize(data_p) != entry["data"]
                or os.path.getsize(keys_p) != entry["keys"]
                or os.path.getsize(offs_p) != entry["offs"]
                or ("idx" in entry and os.path.getsize(idx_p) != entry["idx"])
            ):
                return None
        except OSError:
            return None
    return doc


# Per-run (start, stop) record-index cuts defining one key range.
RangeCut = List[Tuple[int, int]]


def plan_ranges(runs: Sequence[Run], budget: int) -> List[RangeCut]:
    """Partition the union of sorted runs into key ranges of ≤ ``budget``
    record-stream bytes each (best effort: a single record larger than the
    budget still forms a 1-record range so progress is guaranteed).

    Ranges are disjoint, cover everything, and are emitted in ascending key
    order; ties are never reordered across ranges (in-tie splits cut in run
    order, matching the stable merge's tie order).
    """
    R = len(runs)
    i = [0] * R
    out: List[RangeCut] = []

    def remaining() -> bool:
        return any(i[r] < runs[r].n for r in range(R))

    def cut_at_value(v: int) -> List[int]:
        """Per-run index of the first key > v (take everything ≤ v).

        Clamped to the current position: after an in-tie split, part of a
        tie is already consumed, and an unclamped searchsorted would point
        *before* ``i[r]`` (negative byte counts, non-termination).
        """
        return [
            max(
                i[r],
                int(np.searchsorted(runs[r].keys, v, side="right")),
            )
            for r in range(R)
        ]

    def nbytes(j: List[int]) -> int:
        return sum(runs[r].bytes_between(i[r], j[r]) for r in range(R))

    while remaining():
        lo_v = min(
            int(runs[r].keys[i[r]]) for r in range(R) if i[r] < runs[r].n
        )
        hi_v = max(
            int(runs[r].keys[runs[r].n - 1])
            for r in range(R)
            if i[r] < runs[r].n
        )
        if nbytes([runs[r].n for r in range(R)]) <= budget:
            out.append([(i[r], runs[r].n) for r in range(R)])
            break
        # Largest v with bytes(keys ≤ v) ≤ budget, by value bisection.
        lo, hi = lo_v - 1, hi_v
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if nbytes(cut_at_value(mid)) <= budget:
                lo = mid
            else:
                hi = mid - 1
        j = cut_at_value(lo)
        if nbytes(j) == 0:
            # The single smallest remaining key's tie exceeds the budget:
            # split inside the tie, consuming runs in order (stability).
            j = list(i)
            rem = budget
            progressed = False
            for r in range(R):
                if i[r] >= runs[r].n or int(runs[r].keys[i[r]]) != lo_v:
                    continue
                stop = int(
                    np.searchsorted(runs[r].keys, lo_v, side="right")
                )
                k = i[r]
                while k < stop:
                    rec = runs[r].bytes_between(k, k + 1)
                    if rec > rem and progressed:
                        break
                    rem -= rec
                    k += 1
                    progressed = True
                j[r] = k
                if k < stop:
                    break  # budget exhausted mid-tie in run order
        out.append([(i[r], j[r]) for r in range(R)])
        i = j
    return out
