"""DeviceStream: one fused device graph with double-buffered split pipelining.

ROADMAP open item #1.  The built device path — the lockstep-lane codec
tiers (PR 1/2/4), the device parse (PR 4), the resident part writes
(PR 5), the serve arena/batcher (PR 6) — historically stitched through
host Python between every stage, with the gates, residency handles,
deadline checks and ledger calls scattered across ``ops/flate.py``,
``io/bam.py``, ``pipeline.py`` and ``serve/batching.py``.  This module
is the consolidation: a :class:`DeviceStream` owns, in one place,

- the **codec tier policy** (:class:`StreamPolicy`): the inflate-lanes /
  deflate-lanes / device-write gates resolved once per stream, with the
  pipelined-mode relaxation of the local-latency auto rule — a ≥2-deep
  pipeline keeps that many launches in flight, so per-launch RTT hides
  behind the other splits' compute and the effective gate is
  ``depth × hadoopbam.device.auto-rtt-ms`` (base default unchanged);
- the **residency handle**: every attach/transfer/release of a
  device-resident buffer a stream client makes goes through the
  :data:`~hadoop_bam_tpu.utils.hbm.LEDGER` via this object, so the
  PR 11 leak/double-copy instruments see one consistent holder story;
- the **deadline check** (:meth:`check_deadline`): the request's
  end-to-end budget is re-checked between pipeline stages — a split
  never uploads, parses or encodes on a spent budget;
- the **transfer ledger**: h2d/d2h crossings ride the existing
  ``utils.tracing.count_h2d``/``count_d2h`` seams of the ops the stream
  drives, so the round artifacts keep one source of PCIe truth.

The **double-buffered drive** (:meth:`read_splits`) streams splits
through a read-ahead pool ``depth`` deep (``hadoopbam.read.depth`` conf
key → ``HBAM_READ_DEPTH`` env → 2): split *k+1*'s file read, h2d upload
and device inflate/parse kernels dispatch while split *k*'s host-side
batch assembly runs, and the part-write d2h rides the lazily-awaited
async fetches (``pipeline._LazyPermFetch``, the executor's concurrent
part encoders).  Between stages the stream **donates** buffers
(``jax.jit(..., donate_argnums=…)``) so HBM never holds two copies of a
split:

- *inflate→parse*: the split window is donated into the chain kernel's
  padded parse stream (:meth:`parse_split`) when the write path will not
  gather from it;
- *windows→write stream*: the per-split windows are donated into the
  flat write-stream concat (:func:`donating_concat`, used by
  ``io.bam.ChunkedRecords.from_batches``);
- *gather→deflate*: the gathered part column is donated into its final
  reader, the on-chip CRC launch
  (``ops.flate.bgzf_compress_device(donate_input=True)``).

Backends without donation support (the CPU/interpret CI) run the same
code minus the aliasing (``utils.backend.donation_supported``); the
PR 11 double-copy detector is the regression guard either way.

Disarmed contract: with every device tier off, a DeviceStream is a plain
read-ahead pool — zero ``device_stream.*`` counters move and the output
is byte-identical (asserted in tests/test_device_stream.py).
"""

from __future__ import annotations

import functools
import os
from typing import Iterator, Optional, Sequence

import numpy as np

from .utils.hbm import LEDGER
from .utils.tracing import METRICS, span, trace_ctx

#: Read-ahead depth when neither the argument, the conf key nor the env
#: var says otherwise (measured neutral-to-positive even on the 1-core
#: bench host — BENCH_NOTES.md).
DEFAULT_DEPTH = 2


def resolve_depth(conf=None, depth: Optional[int] = None) -> int:
    """The split-pipelining depth: explicit argument →
    ``hadoopbam.read.depth`` conf key → ``HBAM_READ_DEPTH`` env var →
    :data:`DEFAULT_DEPTH`.  Malformed overrides keep the default; the
    floor is 1 (no read-ahead)."""
    if depth is not None:
        return max(1, int(depth))
    if conf is not None:
        from .conf import READ_DEPTH

        v = conf.get_int(READ_DEPTH, 0)
        if v > 0:
            return v
    env = os.environ.get("HBAM_READ_DEPTH")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            return DEFAULT_DEPTH
    return DEFAULT_DEPTH


class StreamPolicy:
    """The codec tier gates, resolved once per stream.

    ``effective_rtt_ms`` is the auto rule's gate after the pipelined
    relaxation: ``depth × device_auto_rtt_ms`` for a ≥2-deep stream
    (each in-flight split hides one launch RTT), the plain base value
    otherwise.  Env forces and explicit conf keys still short-circuit
    the RTT gate entirely, exactly as before."""

    def __init__(
        self,
        inflate_lanes: bool,
        deflate_lanes: bool,
        device_write: bool,
        depth: int,
        auto_rtt_ms: float,
        effective_rtt_ms: float,
        use_rans_lanes: bool = False,
        use_bcf_chain: bool = False,
    ) -> None:
        self.inflate_lanes = inflate_lanes
        self.deflate_lanes = deflate_lanes
        self.device_write = device_write
        self.use_rans_lanes = use_rans_lanes
        self.use_bcf_chain = use_bcf_chain
        self.depth = depth
        self.auto_rtt_ms = auto_rtt_ms
        self.effective_rtt_ms = effective_rtt_ms

    @property
    def armed(self) -> bool:
        return (
            self.inflate_lanes
            or self.deflate_lanes
            or self.device_write
            or self.use_rans_lanes
            or self.use_bcf_chain
        )

    @classmethod
    def resolve(cls, conf=None, depth: Optional[int] = None) -> "StreamPolicy":
        from .ops import flate

        d = resolve_depth(conf, depth)
        base = flate.device_auto_rtt_ms(conf)
        eff = base * d if d >= 2 else base
        return cls(
            inflate_lanes=flate.lanes_tier_enabled(conf, max_rtt_ms=eff),
            deflate_lanes=flate.deflate_lanes_tier_enabled(
                conf, max_rtt_ms=eff
            ),
            device_write=flate.device_write_enabled(conf, max_rtt_ms=eff),
            depth=d,
            auto_rtt_ms=base,
            effective_rtt_ms=eff,
            use_rans_lanes=flate.rans_lanes_tier_enabled(
                conf, max_rtt_ms=eff
            ),
            use_bcf_chain=flate.bcf_chain_tier_enabled(
                conf, max_rtt_ms=eff
            ),
        )


@functools.lru_cache(maxsize=None)
def _slice_pad_fn(n_bytes: int, pad_len: int, donate: bool):
    """Jitted slice+pad of a split window to the chain kernel's chunk
    geometry, optionally donating the window — the inflate→parse seam.
    Cached per (length, padding) pair: the same shapes the eager
    ``jnp.pad(dd[s0:s1], …)`` it replaces compiled per call anyway."""
    import jax

    def f(d, s0):
        import jax.numpy as jnp

        sl = jax.lax.dynamic_slice_in_dim(d, s0, n_bytes)
        return jnp.pad(sl, (0, pad_len - n_bytes))

    return jax.jit(f, donate_argnums=(0,) if donate else ())


@functools.lru_cache(maxsize=None)
def _concat_fn(n_parts: int, donate: bool):
    """Jitted device-to-device concat of per-split windows into the flat
    write stream, donors donated — the windows→write-stream seam."""
    import jax

    def f(*xs):
        import jax.numpy as jnp

        return jnp.concatenate(xs)

    return jax.jit(
        f, donate_argnums=tuple(range(n_parts)) if donate else ()
    )


def donating_concat(parts: Sequence):
    """Concatenate device-resident windows into one flat stream,
    donating the donors when the backend supports it, so HBM holds the
    windows *or* the flat stream — not both — during the write-phase
    setup (the double-copy window ``ChunkedRecords.from_batches`` used
    to open physically even though the ledger adopt closed it
    logically).  Ledger bookkeeping stays the caller's (``adopt``)."""
    from .utils.backend import donation_supported

    donate = donation_supported()
    out = _concat_fn(len(parts), donate)(*parts)
    if donate:
        METRICS.count("device_stream.concat_donations", 1)
    return out


class DeviceStream:
    """One job's fused device pipeline: tier policy + residency +
    deadline + transfer accounting, driving the split stream
    double-buffered.

    Clients: ``pipeline.sort_bam`` (the read drive, the parse seam, the
    part encodes), ``io.bam.read_split``/``write_part_fast`` (codec tier
    + residency attach), and the serve daemon's ``HbmArena`` and
    ``LaneBatcher`` (the same decode seam and residency story instead of
    parallel implementations).  A stream is cheap to construct — the
    gates resolve from env/conf/cached-RTT — so one per job (or one per
    daemon) is the intended shape."""

    def __init__(
        self,
        conf=None,
        deadline=None,
        depth: Optional[int] = None,
        name: str = "device_stream",
    ) -> None:
        self.conf = conf
        self.deadline = deadline
        self.name = name
        self.policy = StreamPolicy.resolve(conf, depth)
        self.depth = self.policy.depth

    # -- shared plumbing ----------------------------------------------------

    @property
    def armed(self) -> bool:
        """Any device tier live?  Disarmed streams must move zero
        ``device_stream.*`` counters (the disarmed contract)."""
        return self.policy.armed

    def _count(self, suffix: str, n: int = 1) -> None:
        METRICS.count(f"device_stream.{suffix}", n)

    def check_deadline(self, seam: str) -> None:
        """Between-stage deadline check: raises ``DeadlineExceeded``
        instead of spending device time on an expired request.  Costs
        one ``is None`` branch in batch mode."""
        if self.deadline is not None:
            self.deadline.check(seam)

    # -- the residency handle (the ledger, in one place) --------------------

    def register(self, obj, kind: str, holder: str, **kw):
        return LEDGER.register(obj, kind, holder, **kw)

    def transfer(self, obj, holder: str, kind: Optional[str] = None):
        return LEDGER.transfer(obj, holder, kind=kind)

    def adopt(self, obj, kind: str, holder: str, donors=(), **kw):
        return LEDGER.adopt(obj, kind, holder, donors=donors, **kw)

    def release(self, obj) -> bool:
        return LEDGER.release(obj)

    def attach_window(self, dev, holder: str = "bam.split_window"):
        """The inflate tier left a split window in HBM: the stream hands
        ownership to the reader's batch (counted, ledgered)."""
        if dev is None:
            return None
        self._count("windows")
        return LEDGER.transfer(dev, holder)

    @staticmethod
    def release_batch(b) -> None:
        """Give a batch's HBM-resident window back through the ledger
        and drop the reference (the one release helper every drop site
        shares — ``pipeline._release_split_residency`` delegates here)."""
        dd = getattr(b, "device_data", None)
        if dd is not None:
            LEDGER.release(dd)
        b.device_data = None

    # -- the codec seam (split readers + the serve lane batcher) ------------

    def decode_members(
        self,
        data,
        coffsets,
        csizes,
        usizes,
        return_device: bool = False,
        threads: Optional[int] = None,
        on_error: str = "raise",
    ):
        """Decode a batch of BGZF members through the stream's tier
        policy — the shared seam behind ``io.bam.read_virtual_range``'s
        window inflate and the serve ``LaneBatcher``'s coalesced
        launches.  Contract of ``native.inflate_blocks``: ``(out,
        out_offsets)``, plus the device-resident window as a third value
        when ``return_device``.

        ``on_error="host"`` tiers a failed device launch down to the
        native codec for the whole call (counting
        ``bam.device_inflate_fallback`` and, for HBM exhaustion,
        ``bam.oom_tierdown`` — the read path's policy); ``"raise"``
        propagates, which is what the serve OOM ladder needs (evict →
        retry → per-request tier-down happens a layer up)."""
        co = np.asarray(coffsets, dtype=np.int64)
        cs = np.asarray(csizes, dtype=np.int32)
        us = np.asarray(usizes, dtype=np.int32)
        if self.policy.inflate_lanes:
            from .ops import flate

            try:
                self._count("decodes")
                if return_device:
                    out, offs, dev = flate.inflate_blocks_device(
                        data, co, cs, us, return_device=True
                    )
                    return out, offs, dev
                return flate.inflate_blocks_device(data, co, cs, us)
            except Exception as e:
                if on_error != "host":
                    raise
                METRICS.count("bam.device_inflate_fallback", 1)
                from .utils.backend import is_resource_exhausted

                if is_resource_exhausted(e):
                    METRICS.count("bam.oom_tierdown", 1)
        from . import native

        out, offs = native.inflate_blocks(data, co, cs, us, threads=threads)
        if return_device:
            return out, offs, None
        return out, offs

    def decompress_cram_blocks(self, blocks, errors: str = "strict"):
        """Decode a batch of CRAM compressed blocks ``(method, payload,
        raw_size)`` through the stream's rANS tier policy — the third
        codec family's seam, behind ``spec.cram.decode_container``.  An
        armed stream routes rANS 4x8 blocks through the lockstep lanes
        (per-slice tier-down to the NumPy host decoder, counted under
        ``cram.rans.*``); a disarmed stream is the plain host batch and
        moves zero ``device_stream.*`` / ``cram.rans.*`` counters."""
        from .spec import cram_codecs

        if self.policy.use_rans_lanes:
            self._count("cram_decodes")
        return cram_codecs.decompress_batch(
            blocks,
            errors=errors,
            conf=self.conf,
            use_lanes=self.policy.use_rans_lanes,
        )

    def walk_bcf_records(self, payload, start: int, limit: int):
        """Walk a BCF record chain through the stream's tier policy — the
        fourth codec family's seam, behind ``io.bcf.read_split``.  An
        armed stream runs the device chain kernel
        (``ops.pallas.bcf_chain``) with per-window tier-down to the
        bit-exact NumPy walk; a disarmed stream returns ``None`` and the
        caller keeps the pre-existing host path byte-for-byte (the
        disarmed contract: zero ``device_stream.*``/``bcf.*`` counters).

        Returns ``(cols, count, ok, tier)`` from
        :func:`~hadoop_bam_tpu.ops.pallas.bcf_chain.walk_chain`, or
        ``None`` when the tier is off."""
        if not self.policy.use_bcf_chain:
            return None
        from .ops.pallas.bcf_chain import walk_chain

        self._count("bcf_walks")
        return walk_chain(payload, start, limit)

    def deflate_stream(
        self, payload, level: int = 1, block_payload: Optional[int] = None
    ) -> bytes:
        """Compress a host byte stream into back-to-back BGZF members
        (no terminator) through the stream's deflate tier policy — the
        mesh shuffle's sender seam.  A lanes-armed stream rides
        ``deflate_blocks_device`` (per-member host-zlib tier-down as
        everywhere else, including the forced-tier-down fault seam); an
        unarmed stream uses the native host codec directly — real
        compression either way, and the member blocking (a cut every
        ``block_payload`` bytes) is identical, so the caller's member
        table math holds across tiers."""
        if self.policy.deflate_lanes:
            from .ops import flate

            self._count("deflates")
            return flate.deflate_blocks_device(
                np.asarray(payload),
                level=level,
                block_payload=block_payload,
                use_lanes=True,
                conf=self.conf,
            )
        from . import native

        kw = {} if block_payload is None else {"block_payload": block_payload}
        return native.deflate_blocks(payload, level=level, **kw)

    # -- the double-buffered split drive ------------------------------------

    def read_splits(
        self,
        fmt,
        splits,
        fields=None,
        depth: Optional[int] = None,
        with_keys: bool = True,
        errors: Optional[str] = None,
    ) -> Iterator:
        """Yield decoded split batches in order, double-buffered: a
        read-ahead pool ``depth`` deep keeps the next splits' file reads,
        h2d uploads and device inflate kernels in flight while the
        caller processes the current one.  The file read and the native
        inflate release the GIL, and the device tiers dispatch
        asynchronously, so on a lanes-armed stream split *k+1*'s upload
        rides under split *k*'s host-side work — the h2d leg of the
        double buffer (the d2h leg is the lazily-awaited perm fetch and
        the executor's concurrent part encodes).

        The chosen depth is published as the ``pipeline.read_depth``
        gauge (surfaced by the run manifest).  The deadline is checked
        once per split *between* stages — before the result wait — so an
        expired request stops at a stage boundary instead of mid-kernel.

        Under ``errors="salvage"`` a split whose read fails outright
        degrades to an *empty batch* with a ``salvage.splits_failed``
        counter instead of killing the job (yield order is preserved —
        the double-buffer ordering drills pin this)."""
        d = max(1, int(depth)) if depth is not None else self.depth
        METRICS.set_gauge("pipeline.read_depth", d)
        METRICS.set_gauge("pipeline.auto_rtt_ms", self.policy.auto_rtt_ms)
        METRICS.set_gauge(
            "pipeline.effective_rtt_ms", self.policy.effective_rtt_ms
        )
        if self.armed:
            self._count("splits", len(splits))

        def read_one(si, s):
            # trace_ctx tags every stage event this split's read/inflate/
            # parse/key chain emits (in whichever pool thread it runs)
            # with the split index — the stall reducer's per-item
            # attribution.
            with trace_ctx(split=si), span(
                "pipeline.stage.read_split", category="item"
            ):
                try:
                    return fmt.read_split(
                        s,
                        fields=fields,
                        with_keys=with_keys,
                        errors=errors,
                        stream=self,
                    )
                except Exception:
                    if errors != "salvage":
                        raise
                    METRICS.count("salvage.splits_failed", 1)
                    from .io.bam import RecordBatch, _empty_soa

                    return RecordBatch(
                        soa=_empty_soa(fields),
                        data=np.empty(0, np.uint8),
                        keys=np.empty(0, np.int64),
                    )

        if d <= 1 or len(splits) <= 1:
            for si, s in enumerate(splits):
                self.check_deadline("stream_read")
                yield read_one(si, s)
            return
        from concurrent.futures import ThreadPoolExecutor

        pool = ThreadPoolExecutor(max_workers=d)
        futs = [
            pool.submit(read_one, si, s)
            for si, s in enumerate(splits[: d + 1])
        ]
        nxt = d + 1
        try:
            for i in range(len(splits)):
                # Stage boundary: an expired deadline stops here, before
                # blocking on (or dispatching more) device work.
                self.check_deadline("stream_read")
                b = futs[i].result()
                # Drop the Future (and with it the decoded batch it
                # retains) so only ~depth+1 batches are ever alive: the
                # external-sort path counts on this generator being
                # O(depth), not O(file).
                futs[i] = None
                if nxt < len(splits):
                    futs.append(pool.submit(read_one, nxt, splits[nxt]))
                    nxt += 1
                yield b
                del b
        finally:
            # On a decode error (or the consumer abandoning the
            # generator), don't block on — or keep paying for — reads
            # nobody will use.
            for f in futs:
                if f is not None:
                    f.cancel()
            pool.shutdown(wait=False, cancel_futures=True)

    # -- the inflate→parse seam ---------------------------------------------

    def default_device_parse(self) -> bool:
        """Auto rule for the device-resident parse: a real TPU whose RTT
        passes the (pipelined-relaxed) gate — the stream's version of
        ``pipeline._default_device_parse``."""
        import jax

        try:
            if jax.default_backend() != "tpu":
                return False
            from .utils.backend import device_roundtrip_ms

            return device_roundtrip_ms() < self.policy.effective_rtt_ms
        except Exception:
            return False

    def parse_split(self, b, keep_residency: bool = False):
        """Upload (or donate) one split's record stream and launch the
        on-chip parse.

        Returns ``(hi, lo, unmapped, meta)`` device arrays (``meta`` =
        ``[count, ok, n_unmapped]`` int32), sliced to the host-known
        record count; ``None`` for an empty split; ``False`` when the
        stream is outside the kernel's int32 domain (caller falls back
        to host keys).  Everything is dispatched asynchronously — the
        chip walks the chain and builds keys while the host inflates the
        next split.

        When the split carries HBM residency, the window is sliced+
        padded on device (no h2d at all); unless ``keep_residency`` (the
        device write path still needs the window for its part gathers),
        the window is *donated* into the padded parse stream — the
        inflate→parse donation seam — so HBM never holds the window and
        the parse stream at once, and the ledger records the handoff as
        an adopt (donor closed, successor registered)."""
        from .ops.decode import keys_from_stream_device
        from .ops.pallas.chain import CHUNK

        import jax.numpy as jnp

        n_i = b.n_records
        if n_i == 0:
            return None
        self.check_deadline("stream_parse")
        rec_off = b.soa["rec_off"]
        rec_len = b.soa["rec_len"]
        # The batch window may hold bytes before the first record (split
        # vstart inside a block) and after the last (spill margin): slice
        # the exact back-to-back record stream, pre-padded to the chain
        # kernel's chunk geometry so only a handful of shapes compile.
        s0 = int(rec_off[0]) - 4
        s1 = int(rec_off[-1] + rec_len[-1])
        n_bytes = s1 - s0
        if n_bytes > 2**31 - CHUNK:
            # Past the chain kernel's int32 offset domain (only reachable
            # with a multi-GiB split_size): host keys for the whole job.
            return False
        n_chunks = max(1, -(-n_bytes // CHUNK))
        pad_len = n_chunks * CHUNK + 256 * 4
        dd = getattr(b, "device_data", None)
        if dd is not None:
            # On-chip output residency: the split's inflated bytes are
            # already in HBM (left there by the lockstep-lane inflate
            # tier) — slice+pad on device and skip the h2d entirely.
            if not keep_residency:
                from .utils.backend import donation_supported

                donate = donation_supported()
                padded = _slice_pad_fn(n_bytes, pad_len, donate)(dd, s0)
                # Ledger: the parse stream succeeds the window (donor
                # closed, successor registered); its own residency ends
                # when the chain kernel's outputs are all that remain.
                padded = LEDGER.adopt(
                    padded,
                    kind="parse_stream",
                    holder=f"{self.name}.parse",
                    donors=[dd],
                    nbytes=pad_len,
                )
                b.device_data = None
                if donate:
                    self._count("parse_donations")
            else:
                padded = jnp.pad(dd[s0:s1], (0, pad_len - n_bytes))
            METRICS.count("sort_bam.device_parse_residency", 1)
        else:
            padded = np.zeros(pad_len, dtype=np.uint8)
            padded[:n_bytes] = b.data[s0:s1]
            from .utils.tracing import count_h2d

            count_h2d(padded.nbytes, "parse_stream")
        hi, lo, unm, count, ok = keys_from_stream_device(padded, n_bytes)
        if dd is not None and not keep_residency:
            # The chain kernel's outputs are dispatched; the parse
            # stream's explicit residency ends here (jax frees the
            # buffer when the kernel completes).
            LEDGER.release(padded)
        meta = jnp.stack(
            [
                count.astype(jnp.int32),
                ok.astype(jnp.int32),
                jnp.sum(unm).astype(jnp.int32),
            ]
        )
        return hi[:n_i], lo[:n_i], unm[:n_i], meta

    # -- the gather→deflate seam --------------------------------------------

    def encode_part(
        self,
        batch,
        order: Optional[np.ndarray],
        dup_mask: Optional[np.ndarray],
        level: int,
    ) -> Optional[bytes]:
        """The device-resident part assembly: sorted gather + markdup
        flag patch on chip (``ops.pallas.gather_stream``), per-member
        CRC32 on chip (``ops.pallas.crc32``), deflate lanes fed
        device-to-device — the only d2h traffic is the compressed part
        blob (+ CRC column).  The gathered column is donated into its
        final reader, the CRC launch (the gather→deflate donation seam),
        so on donation-capable backends the part's uncompressed bytes
        free as the encode dispatches.

        Returns the part blob (always lanes-blocked at
        ``DEV_LZ_PAYLOAD``), or ``None`` to tier down to the host gather
        path; every tier-down records its reason
        (``bam.device_write_tierdown.{no_residency,size}`` /
        ``bam.device_write_fallback``) so a silently-dead path shows up
        in the round artifacts."""
        from .io.bam import ChunkedRecords
        from .ops import flate as _flate

        if isinstance(batch, ChunkedRecords):
            if batch.device_flat is None:
                METRICS.count("bam.device_write_tierdown.no_residency", 1)
                return None
            stream_dev = batch.device_flat
            base = batch.chunk_base[
                np.asarray(batch.chunk_id, dtype=np.int64)
            ]
            src = base + np.asarray(batch.soa["rec_off"], np.int64) - 4
        else:
            if getattr(batch, "device_data", None) is None:
                METRICS.count("bam.device_write_tierdown.no_residency", 1)
                return None
            stream_dev = batch.device_data
            src = np.asarray(batch.soa["rec_off"], np.int64) - 4
        lens = np.asarray(batch.soa["rec_len"], np.int64) + 4
        if order is not None:
            src = src[order]
            lens = lens[order]
        if len(src) == 0:
            return None  # empty part: the host path writes its canonical form
        self.check_deadline("stream_encode")
        dm = None
        if dup_mask is not None:
            dm = dup_mask[order] if order is not None else dup_mask
            if not dm.any():
                dm = None
        gathered = None
        try:
            from .ops.pallas.gather_stream import gather_stream_device

            gathered, _ = gather_stream_device(
                stream_dev, src, lens, dup_mask=dm
            )
            # The permuted gather column is a second resident stream for
            # the duration of the deflate — ledgered so the HBM track
            # shows the write-phase bump and a dropped release would be
            # named.  Its buffer is donated into the CRC launch below.
            LEDGER.register(
                gathered, kind="write_gather", holder="bam.device_write"
            )
            blob = _flate.deflate_blocks_device(
                None,
                level=level,
                block_payload=_flate.DEV_LZ_PAYLOAD,
                use_lanes=True,
                conf=self.conf,
                device_input=gathered,
                donate_input=True,
            )
        except ValueError:
            METRICS.count("bam.device_write_tierdown.size", 1)
            return None
        except Exception:
            # Never fatal to a write — the host gather path is bit-correct.
            METRICS.count("bam.device_write_fallback", 1)
            return None
        finally:
            if gathered is not None:
                LEDGER.release(gathered)
        if dm is not None:
            METRICS.count("bam.duplicate_flags_patched", int(dm.sum()))
        METRICS.count("bam.device_write_parts", 1)
        self._count("parts_encoded")
        return blob
