"""CRAM encoding codecs: bit I/O, the encoding family, rANS 4x8.

The decode machinery htsjdk's CRAM codec stack provides below the reference's
CRAMRecordReader (CRAMRecordReader.java:43-88 drives htsjdk's CRAMIterator).
Implements the CRAM 2.1/3.0 encoding ids used by htsjdk/htslib-written files:

  0 NULL, 1 EXTERNAL, 3 HUFFMAN, 4 BYTE_ARRAY_LEN, 5 BYTE_ARRAY_STOP,
  6 BETA, 7 SUBEXP, 9 GAMMA

plus block compression: raw, gzip, bzip2, lzma, and the rANS-4x8 order-0/1
entropy codec introduced in CRAM 3.0.
"""

from __future__ import annotations

import bz2
import gzip
import lzma
import struct
from typing import Dict, List, Optional, Tuple

from .cram import CramError, read_itf8


# ---------------------------------------------------------------------------
# Bit I/O over the core block (MSB first)
# ---------------------------------------------------------------------------


class BitReader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0  # bit position

    def read_bit(self) -> int:
        byte = self.data[self.pos >> 3]
        bit = (byte >> (7 - (self.pos & 7))) & 1
        self.pos += 1
        return bit

    def read_bits(self, n: int) -> int:
        v = 0
        for _ in range(n):
            v = (v << 1) | self.read_bit()
        return v


# ---------------------------------------------------------------------------
# Block (de)compression
# ---------------------------------------------------------------------------

METHOD_RAW = 0
METHOD_GZIP = 1
METHOD_BZIP2 = 2
METHOD_LZMA = 3
METHOD_RANS = 4


def decompress(method: int, data: bytes, raw_size: int) -> bytes:
    if method == METHOD_RAW:
        return data
    if method == METHOD_GZIP:
        return gzip.decompress(data)
    if method == METHOD_BZIP2:
        return bz2.decompress(data)
    if method == METHOD_LZMA:
        return lzma.decompress(data)
    if method == METHOD_RANS:
        return rans_decode(data, raw_size)
    raise CramError(f"unsupported CRAM block compression method {method}")


def compress(method: int, data: bytes) -> bytes:
    if method == METHOD_RAW:
        return data
    if method == METHOD_GZIP:
        return gzip.compress(data, 6)
    if method == METHOD_BZIP2:
        return bz2.compress(data)
    if method == METHOD_LZMA:
        return lzma.compress(data)
    raise CramError(f"unsupported write compression method {method}")


# ---------------------------------------------------------------------------
# rANS 4x8 (CRAM 3.0): order-0 and order-1 decode
# ---------------------------------------------------------------------------

_RANS_L = 1 << 23
_TF_SHIFT = 12
_TOTFREQ = 1 << _TF_SHIFT


def _read_freq(data: bytes, p: int) -> Tuple[int, int]:
    """Frequency: 1 byte, or 2 bytes when the first has the top bit set."""
    f = data[p]
    p += 1
    if f >= 0x80:
        f = ((f & 0x7F) << 8) | data[p]
        p += 1
    return f, p


def _read_freq_table0(data: bytes, p: int) -> Tuple[List[int], int]:
    """Order-0 table with the sym/RLE layout of rANS_static.c."""
    F = [0] * 256
    sym = data[p]
    p += 1
    rle = 0
    while True:
        F[sym], p = _read_freq(data, p)
        if rle > 0:
            rle -= 1
            sym += 1
        else:
            nxt = data[p]
            p += 1
            if nxt == sym + 1:
                rle = data[p]
                p += 1
            sym = nxt
        if sym == 0:
            break
    return F, p


def _cum(F: List[int]) -> Tuple[List[int], bytes]:
    C = [0] * 257
    for i in range(256):
        C[i + 1] = C[i] + F[i]
    lookup = bytearray(_TOTFREQ)
    for s in range(256):
        if F[s]:
            lookup[C[s] : C[s] + F[s]] = bytes([s]) * F[s]
    return C, bytes(lookup)


def rans_decode(data: bytes, raw_size: int) -> bytes:
    if not data:
        if raw_size == 0:
            return b""
        raise CramError("empty rANS stream")
    order = data[0]
    (n_in,) = struct.unpack_from("<I", data, 1)
    (n_out,) = struct.unpack_from("<I", data, 5)
    if n_out != raw_size:
        # trust the stream header; raw_size is advisory
        pass
    p = 9
    if order == 0:
        return _rans_decode0(data, p, n_out)
    if order == 1:
        return _rans_decode1(data, p, n_out)
    raise CramError(f"unknown rANS order {order}")


def _rans_decode0(data: bytes, p: int, n_out: int) -> bytes:
    F, p = _read_freq_table0(data, p)
    C, lookup = _cum(F)
    R = list(struct.unpack_from("<4I", data, p))
    p += 16
    out = bytearray(n_out)
    mask = _TOTFREQ - 1
    for i in range(n_out):
        j = i & 3
        m = R[j] & mask
        s = lookup[m]
        out[i] = s
        R[j] = F[s] * (R[j] >> _TF_SHIFT) + m - C[s]
        while R[j] < _RANS_L:
            R[j] = (R[j] << 8) | data[p]
            p += 1
    return bytes(out)


def _rans_decode1(data: bytes, p: int, n_out: int) -> bytes:
    # outer table: context symbols with the same RLE layout
    Fs: Dict[int, Tuple[List[int], List[int], bytes]] = {}
    ctx = data[p]
    p += 1
    rle = 0
    while True:
        F, p = _read_freq_table0(data, p)
        C, lookup = _cum(F)
        Fs[ctx] = (F, C, lookup)
        if rle > 0:
            rle -= 1
            ctx += 1
        else:
            nxt = data[p]
            p += 1
            if nxt == ctx + 1:
                rle = data[p]
                p += 1
            ctx = nxt
        if ctx == 0:
            break
    R = list(struct.unpack_from("<4I", data, p))
    p += 16
    out = bytearray(n_out)
    q4 = n_out >> 2
    idx = [0, q4, 2 * q4, 3 * q4]
    last = [0, 0, 0, 0]
    mask = _TOTFREQ - 1
    empty = ([0] * 256, [0] * 257, bytes(_TOTFREQ))
    # stream 3 also covers the remainder tail
    limits = [q4, q4, q4, n_out - 3 * q4]
    done = 0
    step = 0
    while done < 4:
        done = 0
        for j in range(4):
            if step >= limits[j]:
                done += 1
                continue
            F, C, lookup = Fs.get(last[j], empty)
            m = R[j] & mask
            s = lookup[m]
            out[idx[j] + step] = s
            R[j] = F[s] * (R[j] >> _TF_SHIFT) + m - C[s]
            while R[j] < _RANS_L:
                R[j] = (R[j] << 8) | data[p]
                p += 1
            last[j] = s
        step += 1
    return bytes(out)


# ---------------------------------------------------------------------------
# Encoding family
# ---------------------------------------------------------------------------

ENC_NULL = 0
ENC_EXTERNAL = 1
ENC_GOLOMB = 2
ENC_HUFFMAN = 3
ENC_BYTE_ARRAY_LEN = 4
ENC_BYTE_ARRAY_STOP = 5
ENC_BETA = 6
ENC_SUBEXP = 7
ENC_GOLOMB_RICE = 8
ENC_GAMMA = 9


class ExternalStream:
    """One external block's payload with a read cursor."""

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def read_byte(self) -> int:
        b = self.data[self.pos]
        self.pos += 1
        return b

    def read_bytes(self, n: int) -> bytes:
        b = self.data[self.pos : self.pos + n]
        if len(b) != n:
            raise CramError("external stream exhausted")
        self.pos += n
        return b

    def read_itf8(self) -> int:
        v, self.pos = read_itf8(self.data, self.pos)
        return v

    def read_until(self, stop: int) -> bytes:
        i = self.data.index(bytes([stop]), self.pos)
        out = self.data[self.pos : i]
        self.pos = i + 1
        return out


class DecodeContext:
    """Core bit stream + external streams for one slice."""

    def __init__(self, core: bytes, external: Dict[int, bytes]):
        self.core = BitReader(core)
        self.external = {k: ExternalStream(v) for k, v in external.items()}

    def stream(self, cid: int) -> ExternalStream:
        try:
            return self.external[cid]
        except KeyError:
            raise CramError(f"missing external block {cid}")


def parse_encoding(buf: bytes, pos: int) -> Tuple["Encoding", int]:
    codec, pos = read_itf8(buf, pos)
    nparams, pos = read_itf8(buf, pos)
    params = buf[pos : pos + nparams]
    pos += nparams
    return Encoding(codec, bytes(params)), pos


class Encoding:
    """One parsed encoding: decodes ints or byte arrays from a context."""

    def __init__(self, codec: int, params: bytes):
        self.codec = codec
        self.params = params
        self._parse()

    def _parse(self) -> None:
        p = self.params
        c = self.codec
        if c == ENC_EXTERNAL:
            self.content_id, _ = read_itf8(p, 0)
        elif c == ENC_HUFFMAN:
            n, q = read_itf8(p, 0)
            self.symbols = []
            for _ in range(n):
                v, q = read_itf8(p, q)
                self.symbols.append(v)
            m, q = read_itf8(p, q)
            self.lengths = []
            for _ in range(m):
                v, q = read_itf8(p, q)
                self.lengths.append(v)
            self._build_huffman()
        elif c == ENC_BYTE_ARRAY_LEN:
            self.len_enc, q = parse_encoding(p, 0)
            self.val_enc, _ = parse_encoding(p, q)
        elif c == ENC_BYTE_ARRAY_STOP:
            self.stop = p[0]
            self.content_id, _ = read_itf8(p, 1)
        elif c == ENC_BETA:
            self.offset, q = read_itf8(p, 0)
            self.nbits, _ = read_itf8(p, q)
        elif c == ENC_SUBEXP:
            self.offset, q = read_itf8(p, 0)
            self.k, _ = read_itf8(p, q)
        elif c == ENC_GAMMA:
            self.offset, _ = read_itf8(p, 0)
        elif c == ENC_GOLOMB or c == ENC_GOLOMB_RICE:
            self.offset, q = read_itf8(p, 0)
            self.m, _ = read_itf8(p, q)
        elif c == ENC_NULL:
            pass
        else:
            raise CramError(f"unsupported encoding id {c}")

    def _build_huffman(self) -> None:
        # canonical codes: sort by (length, symbol)
        pairs = sorted(zip(self.lengths, self.symbols))
        self._codes: Dict[Tuple[int, int], int] = {}
        code = 0
        prev_len = 0
        for ln, sym in pairs:
            code <<= ln - prev_len
            prev_len = ln
            self._codes[(ln, code)] = sym
            code += 1
        self._zero_bit = len(pairs) == 1 and pairs[0][0] == 0
        self._single = pairs[0][1] if self._zero_bit else None
        self._max_len = max(self.lengths) if self.lengths else 0

    # -- int decode ----------------------------------------------------------

    def read_int(self, ctx: DecodeContext) -> int:
        c = self.codec
        if c == ENC_EXTERNAL:
            return ctx.stream(self.content_id).read_itf8()
        if c == ENC_HUFFMAN:
            if self._zero_bit:
                return self._single  # type: ignore[return-value]
            code = 0
            ln = 0
            while ln <= self._max_len:
                code = (code << 1) | ctx.core.read_bit()
                ln += 1
                sym = self._codes.get((ln, code))
                if sym is not None:
                    return sym
            raise CramError("bad huffman code")
        if c == ENC_BETA:
            return ctx.core.read_bits(self.nbits) - self.offset
        if c == ENC_GAMMA:
            n = 0
            while ctx.core.read_bit() == 0:
                n += 1
            v = 1
            for _ in range(n):
                v = (v << 1) | ctx.core.read_bit()
            return v - self.offset
        if c == ENC_SUBEXP:
            n = 0
            while ctx.core.read_bit() == 1:
                n += 1
            if n == 0:
                v = ctx.core.read_bits(self.k)
            else:
                v = (1 << (self.k + n - 1)) | ctx.core.read_bits(
                    self.k + n - 1
                )
            return v - self.offset
        raise CramError(f"encoding {c} cannot decode ints")

    # -- byte decode ---------------------------------------------------------

    def read_byte(self, ctx: DecodeContext) -> int:
        c = self.codec
        if c == ENC_EXTERNAL:
            return ctx.stream(self.content_id).read_byte()
        if c in (ENC_HUFFMAN, ENC_BETA, ENC_GAMMA, ENC_SUBEXP):
            return self.read_int(ctx)
        raise CramError(f"encoding {c} cannot decode bytes")

    def read_byte_run(self, ctx: DecodeContext, n: int) -> bytes:
        """``n`` consecutive bytes of this series in one call.

        The hot byte series (QS qualities, BA bases) are EXTERNAL in
        practice — one stream slice instead of n Python calls; a
        zero-bit Huffman constant is one repeat.  Other codecs keep the
        per-byte loop (bit-level state)."""
        if n <= 0:
            return b""
        c = self.codec
        if c == ENC_EXTERNAL:
            return ctx.stream(self.content_id).read_bytes(n)
        if c == ENC_HUFFMAN and self._zero_bit:
            return bytes([self._single]) * n  # type: ignore[list-item]
        return bytes(self.read_byte(ctx) for _ in range(n))

    def read_bytes(self, ctx: DecodeContext, n: Optional[int] = None) -> bytes:
        c = self.codec
        if c == ENC_BYTE_ARRAY_STOP:
            return ctx.stream(self.content_id).read_until(self.stop)
        if c == ENC_BYTE_ARRAY_LEN:
            ln = self.len_enc.read_int(ctx)
            if self.val_enc.codec == ENC_EXTERNAL:
                return ctx.stream(self.val_enc.content_id).read_bytes(ln)
            return bytes(self.val_enc.read_byte(ctx) for _ in range(ln))
        if c == ENC_EXTERNAL:
            if n is None:
                raise CramError("EXTERNAL byte array needs explicit length")
            return ctx.stream(self.content_id).read_bytes(n)
        raise CramError(f"encoding {c} cannot decode byte arrays")


# ---------------------------------------------------------------------------
# Encoding builders (write side)
# ---------------------------------------------------------------------------


def encoding_external(content_id: int) -> bytes:
    from .cram import write_itf8

    params = write_itf8(content_id)
    return write_itf8(ENC_EXTERNAL) + write_itf8(len(params)) + params


def encoding_byte_array_stop(stop: int, content_id: int) -> bytes:
    from .cram import write_itf8

    params = bytes([stop]) + write_itf8(content_id)
    return write_itf8(ENC_BYTE_ARRAY_STOP) + write_itf8(len(params)) + params


def encoding_byte_array_len_external(len_id: int, val_id: int) -> bytes:
    from .cram import write_itf8

    nested_len = encoding_external(len_id)
    nested_val = encoding_external(val_id)
    params = nested_len + nested_val
    return write_itf8(ENC_BYTE_ARRAY_LEN) + write_itf8(len(params)) + params


