"""CRAM encoding codecs: bit I/O, the encoding family, rANS 4x8.

The decode machinery htsjdk's CRAM codec stack provides below the reference's
CRAMRecordReader (CRAMRecordReader.java:43-88 drives htsjdk's CRAMIterator).
Implements the CRAM 2.1/3.0 encoding ids used by htsjdk/htslib-written files:

  0 NULL, 1 EXTERNAL, 3 HUFFMAN, 4 BYTE_ARRAY_LEN, 5 BYTE_ARRAY_STOP,
  6 BETA, 7 SUBEXP, 9 GAMMA

plus block compression: raw, gzip, bzip2, lzma, and the rANS-4x8 order-0/1
entropy codec introduced in CRAM 3.0.
"""

from __future__ import annotations

import bz2
import gzip
import lzma
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .cram import CramError, read_itf8


# ---------------------------------------------------------------------------
# Bit I/O over the core block (MSB first)
# ---------------------------------------------------------------------------


class BitReader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0  # bit position

    def read_bit(self) -> int:
        byte = self.data[self.pos >> 3]
        bit = (byte >> (7 - (self.pos & 7))) & 1
        self.pos += 1
        return bit

    def read_bits(self, n: int) -> int:
        v = 0
        for _ in range(n):
            v = (v << 1) | self.read_bit()
        return v


# ---------------------------------------------------------------------------
# Block (de)compression
# ---------------------------------------------------------------------------

METHOD_RAW = 0
METHOD_GZIP = 1
METHOD_BZIP2 = 2
METHOD_LZMA = 3
METHOD_RANS = 4


class CramUnsupportedCodec(CramError):
    """A block names a compression method this reader does not implement
    (CRAM 3.1 rans-Nx16 / adaptive-arith / fqzcomp / name-tok, or an
    unknown id).  Distinguished from :class:`CramError` so the
    ``errors="salvage"`` policy can quarantine the block instead of
    killing the job (see :func:`decompress_batch`)."""


def decompress(method: int, data: bytes, raw_size: int) -> bytes:
    if method == METHOD_RAW:
        return data
    if method == METHOD_GZIP:
        return gzip.decompress(data)
    if method == METHOD_BZIP2:
        return bz2.decompress(data)
    if method == METHOD_LZMA:
        return lzma.decompress(data)
    if method == METHOD_RANS:
        return rans_decode(data, raw_size)
    raise CramUnsupportedCodec(
        f"unsupported CRAM block compression method {method}"
    )


def compress(method: int, data: bytes) -> bytes:
    if method == METHOD_RAW:
        return data
    if method == METHOD_GZIP:
        return gzip.compress(data, 6)
    if method == METHOD_BZIP2:
        return bz2.compress(data)
    if method == METHOD_LZMA:
        return lzma.compress(data)
    if method == METHOD_RANS:
        # The writer is host-side; pay both orders and keep the smaller
        # (order-1's per-context tables win on sequence/quality series,
        # order-0 on short or near-uniform ones).
        o0 = rans_encode(data, order=0)
        o1 = rans_encode(data, order=1)
        return o1 if len(o1) < len(o0) else o0
    raise CramUnsupportedCodec(f"unsupported write compression method {method}")


# ---------------------------------------------------------------------------
# rANS 4x8 (CRAM 3.0): order-0 and order-1 decode
# ---------------------------------------------------------------------------

_RANS_L = 1 << 23
_TF_SHIFT = 12
_TOTFREQ = 1 << _TF_SHIFT


def _read_freq(data: bytes, p: int) -> Tuple[int, int]:
    """Frequency: 1 byte, or 2 bytes when the first has the top bit set."""
    f = data[p]
    p += 1
    if f >= 0x80:
        f = ((f & 0x7F) << 8) | data[p]
        p += 1
    return f, p


def _read_freq_table0(data: bytes, p: int) -> Tuple[List[int], int]:
    """Order-0 table with the sym/RLE layout of rANS_static.c."""
    F = [0] * 256
    sym = data[p]
    p += 1
    rle = 0
    while True:
        F[sym], p = _read_freq(data, p)
        if rle > 0:
            rle -= 1
            sym += 1
        else:
            nxt = data[p]
            p += 1
            if nxt == sym + 1:
                rle = data[p]
                p += 1
            sym = nxt
        if sym == 0:
            break
    return F, p


def _cum(F: List[int]) -> Tuple[List[int], bytes]:
    C = [0] * 257
    for i in range(256):
        C[i + 1] = C[i] + F[i]
    lookup = bytearray(_TOTFREQ)
    for s in range(256):
        if F[s]:
            lookup[C[s] : C[s] + F[s]] = bytes([s]) * F[s]
    return C, bytes(lookup)


def rans_decode(data: bytes, raw_size: int) -> bytes:
    """Decode one rANS 4x8 stream (NumPy lockstep tier, scalar-oracle
    rescue).  ``raw_size`` is advisory; the stream header's ``n_out``
    wins, exactly as the original per-byte decoder behaved."""
    if not data:
        if raw_size == 0:
            return b""
        raise CramError("empty rANS stream")
    order = data[0]
    (n_out,) = struct.unpack_from("<I", data, 5)
    p = 9
    if order == 0:
        return _rans_decode0(data, p, n_out)
    if order == 1:
        return _rans_decode1(data, p, n_out)
    raise CramError(f"unknown rANS order {order}")


def rans_decode_py(data: bytes, raw_size: int) -> bytes:
    """The original per-byte Python decoder, kept verbatim as the test
    oracle and the last rescue tier (rANS lanes → NumPy host →
    this)."""
    if not data:
        if raw_size == 0:
            return b""
        raise CramError("empty rANS stream")
    order = data[0]
    (n_out,) = struct.unpack_from("<I", data, 5)
    p = 9
    if order == 0:
        return _rans_decode0_py(data, p, n_out)
    if order == 1:
        return _rans_decode1_py(data, p, n_out)
    raise CramError(f"unknown rANS order {order}")


def _rans_decode0_py(data: bytes, p: int, n_out: int) -> bytes:
    F, p = _read_freq_table0(data, p)
    C, lookup = _cum(F)
    R = list(struct.unpack_from("<4I", data, p))
    p += 16
    out = bytearray(n_out)
    mask = _TOTFREQ - 1
    for i in range(n_out):
        j = i & 3
        m = R[j] & mask
        s = lookup[m]
        out[i] = s
        R[j] = F[s] * (R[j] >> _TF_SHIFT) + m - C[s]
        while R[j] < _RANS_L:
            R[j] = (R[j] << 8) | data[p]
            p += 1
    return bytes(out)


def _rans_decode1_py(data: bytes, p: int, n_out: int) -> bytes:
    # outer table: context symbols with the same RLE layout
    Fs: Dict[int, Tuple[List[int], List[int], bytes]] = {}
    ctx = data[p]
    p += 1
    rle = 0
    while True:
        F, p = _read_freq_table0(data, p)
        C, lookup = _cum(F)
        Fs[ctx] = (F, C, lookup)
        if rle > 0:
            rle -= 1
            ctx += 1
        else:
            nxt = data[p]
            p += 1
            if nxt == ctx + 1:
                rle = data[p]
                p += 1
            ctx = nxt
        if ctx == 0:
            break
    R = list(struct.unpack_from("<4I", data, p))
    p += 16
    out = bytearray(n_out)
    q4 = n_out >> 2
    idx = [0, q4, 2 * q4, 3 * q4]
    last = [0, 0, 0, 0]
    mask = _TOTFREQ - 1
    empty = ([0] * 256, [0] * 257, bytes(_TOTFREQ))
    # stream 3 also covers the remainder tail
    limits = [q4, q4, q4, n_out - 3 * q4]
    done = 0
    step = 0
    while done < 4:
        done = 0
        for j in range(4):
            if step >= limits[j]:
                done += 1
                continue
            F, C, lookup = Fs.get(last[j], empty)
            m = R[j] & mask
            s = lookup[m]
            out[idx[j] + step] = s
            R[j] = F[s] * (R[j] >> _TF_SHIFT) + m - C[s]
            while R[j] < _RANS_L:
                R[j] = (R[j] << 8) | data[p]
                p += 1
            last[j] = s
        step += 1
    return bytes(out)


# ---------------------------------------------------------------------------
# rANS 4x8: stream plans + the NumPy lockstep decoder
# ---------------------------------------------------------------------------
#
# Every tier above the Python oracle — the Pallas lanes kernel
# (ops/pallas/rans_lanes.py) and the NumPy host fallback below — shares
# one wave model: a global wave counter ``t`` advances all slices in
# lockstep, each wave decoding exactly one byte per slice with state
#
#   j(t) = t & 3            while t < 4*q4v,
#        = 3                afterwards (the order-1 remainder tail),
#
# where ``q4v = n_out >> 2`` for order-1 and ``ceil(n_out/4)`` for
# order-0 (so order-0 never enters the tail and j cycles 0..3 forever).
# Wave order equals output order for order-0; order-1 output position is
# ``pos(t) = (t&3)*q4 + (t>>2)`` in the quarters and ``pos(t) = t`` in
# the tail — a pure host-side de-interleave after decode.  Renormalizing
# reads at most 2 bytes per wave for any stream the encoder invariants
# allow; a slice needing more (corrupt) flips its ok flag and falls to
# the oracle.


class _RansPlan:
    """Host-parsed header of one rANS 4x8 stream: everything except the
    renorm byte payload (the only part the device kernel touches)."""

    __slots__ = ("order", "n_out", "states", "tables", "payload")

    def __init__(self, order, n_out, states, tables, payload):
        self.order = order
        self.n_out = n_out
        self.states = states  # (R0, R1, R2, R3)
        self.tables = tables  # {ctx: (F[256], C[257], lookup bytes)}
        self.payload = payload  # renorm byte stream

    @property
    def q4v(self) -> int:
        if self.order == 1:
            return self.n_out >> 2
        return (self.n_out + 3) >> 2


def _parse_rans_body(data: bytes, p: int, order: int, n_out: int) -> _RansPlan:
    tables: Dict[int, Tuple[List[int], List[int], bytes]] = {}
    if order == 0:
        F, p = _read_freq_table0(data, p)
        C, lookup = _cum(F)
        tables[0] = (F, C, lookup)
    else:
        ctx = data[p]
        p += 1
        rle = 0
        while True:
            F, p = _read_freq_table0(data, p)
            C, lookup = _cum(F)
            tables[ctx] = (F, C, lookup)
            if rle > 0:
                rle -= 1
                ctx += 1
            else:
                nxt = data[p]
                p += 1
                if nxt == ctx + 1:
                    rle = data[p]
                    p += 1
                ctx = nxt
            if ctx == 0:
                break
    states = struct.unpack_from("<4I", data, p)
    p += 16
    return _RansPlan(order, n_out, states, tables, data[p:])


def parse_rans_plan(data: bytes) -> _RansPlan:
    """Parse the header of one rANS 4x8 stream (order byte, sizes,
    frequency tables, initial states) into a :class:`_RansPlan`.  Raises
    :class:`CramError` on truncated or unknown-order streams."""
    if not data:
        return _RansPlan(0, 0, (_RANS_L,) * 4, {0: _EMPTY_TABLE}, b"")
    try:
        order = data[0]
        if order not in (0, 1):
            raise CramError(f"unknown rANS order {order}")
        (n_out,) = struct.unpack_from("<I", data, 5)
        return _parse_rans_body(data, 9, order, n_out)
    except (IndexError, struct.error):
        raise CramError("truncated rANS stream")


_EMPTY_TABLE = ([0] * 256, [0] * 257, bytes(_TOTFREQ))

#: Sub-batch cap for the NumPy tier: ``B * (NC+1)`` dense context slabs
#: of 4 KiB each; 8192 keeps the lookup bank under ~32 MiB.
_NP_BATCH_SLABS = 8192


def _decode_plans_numpy(plans: Sequence[_RansPlan]):
    """Lockstep-wave NumPy decode of many parsed streams at once.

    Returns ``(outs, ok)``: per-slice decoded bytes (wave-order already
    de-interleaved) and a bool vector — ``ok=False`` marks a slice whose
    stream violated the renorm/cursor invariants (corrupt, or a context
    missing from its table); the caller rescues those through the Python
    oracle so behavior stays bit-exact with it on *every* input.  The
    vectorization win scales with the batch width: all slices advance in
    one wave loop, so the per-wave Python overhead amortizes across the
    batch (the shape the tier-down rescue path actually sees)."""
    B = len(plans)
    outs: List[Optional[bytes]] = [None] * B
    ok_all = np.ones(B, dtype=bool)
    if B == 0:
        return outs, ok_all
    # Sub-batch so the dense per-context banks stay bounded.
    start = 0
    while start < B:
        end = start + 1
        slabs = len(plans[start].tables) + 1
        while end < B:
            nxt = max(slabs, len(plans[end].tables) + 1)
            if (end - start + 1) * nxt > _NP_BATCH_SLABS:
                break
            slabs = nxt
            end += 1
        _decode_plan_group(plans[start:end], outs, ok_all, start)
        start = end
    return outs, ok_all


def _decode_plan_group(plans, outs, ok_all, base):
    B = len(plans)
    n_out = np.array([pl.n_out for pl in plans], dtype=np.int64)
    T = int(n_out.max())
    fourq4 = np.array([4 * pl.q4v for pl in plans], dtype=np.int64)
    clen = np.array([len(pl.payload) for pl in plans], dtype=np.int64)
    maxc = int(clen.max()) if B else 0
    data = np.zeros((B, maxc + 1), dtype=np.int64)
    for b, pl in enumerate(plans):
        if pl.payload:
            data[b, : len(pl.payload)] = np.frombuffer(
                pl.payload, dtype=np.uint8
            )
    R = np.array([pl.states for pl in plans], dtype=np.int64)
    nc = max(len(pl.tables) for pl in plans)
    NC = nc + 1  # one zeroed slab for contexts missing from the table
    lookup = np.zeros((B, NC, _TOTFREQ), dtype=np.uint8)
    Fb = np.zeros((B, NC, 256), dtype=np.int64)
    Cb = np.zeros((B, NC, 256), dtype=np.int64)
    ctx_map = np.full((B, 256), NC - 1, dtype=np.int64)
    missing = np.zeros((B, 256), dtype=bool)
    for b, pl in enumerate(plans):
        # Order-0 ignores context: every prior symbol maps to slab 0.
        missing[b, :] = pl.order == 1
        for ci, (ctx, (F, C, lk)) in enumerate(sorted(pl.tables.items())):
            if pl.order == 1:
                ctx_map[b, ctx] = ci
                missing[b, ctx] = False
            else:
                ctx_map[b, :] = ci
            Fb[b, ci, :] = F
            Cb[b, ci, :] = C[:256]
            lookup[b, ci, :] = np.frombuffer(lk, dtype=np.uint8)
    wave = np.zeros((B, max(T, 1)), dtype=np.uint8)
    last = np.zeros((B, 4), dtype=np.int64)
    p = np.zeros(B, dtype=np.int64)
    ok = np.ones(B, dtype=bool)
    ar = np.arange(B)
    for t in range(T):
        active = t < n_out
        j = np.where(t < fourq4, t & 3, 3)
        Rj = R[ar, j]
        ctx_raw = last[ar, j]
        ok &= ~(active & missing[ar, ctx_raw])
        ci = ctx_map[ar, ctx_raw]
        m = Rj & (_TOTFREQ - 1)
        s = lookup[ar, ci, m].astype(np.int64)
        wave[:, t] = np.where(active, s, 0)
        Rn = Fb[ar, ci, s] * (Rj >> _TF_SHIFT) + m - Cb[ar, ci, s]
        for _ in range(2):
            need = active & (Rn < _RANS_L)
            if need.any():
                byte = data[ar, np.minimum(p, maxc)]
                ok &= ~(need & (p >= clen))
                Rn = np.where(need, (Rn << 8) | byte, Rn)
                p = p + need
        ok &= ~(active & (Rn < _RANS_L))
        R[ar, j] = np.where(active, Rn, Rj)
        last[ar, j] = np.where(active, s, ctx_raw)
    for b, pl in enumerate(plans):
        ok_all[base + b] = ok[b]
        if not ok[b]:
            continue
        outs[base + b] = rans_deinterleave(
            wave[b, : pl.n_out], pl.order, pl.n_out
        )


def rans_deinterleave(w: np.ndarray, order: int, n: int) -> bytes:
    """Wave-order bytes → output-order bytes (shared by the NumPy tier
    and the lanes kernel's host post-pass).  Order-0 wave order *is*
    output order; order-1 interleaves the four quarters."""
    if order == 0 or n < 4:
        return w.tobytes()
    q4 = n >> 2
    t = np.arange(n)
    pos = np.where(t < 4 * q4, (t & 3) * q4 + (t >> 2), t)
    out = np.empty(n, dtype=np.uint8)
    out[pos] = w
    return out.tobytes()


def _rans_decode0(data: bytes, p: int, n_out: int) -> bytes:
    plan = _parse_rans_body(data, p, 0, n_out)
    outs, ok = _decode_plans_numpy([plan])
    if ok[0]:
        return outs[0]
    return _rans_decode0_py(data, p, n_out)


def _rans_decode1(data: bytes, p: int, n_out: int) -> bytes:
    plan = _parse_rans_body(data, p, 1, n_out)
    outs, ok = _decode_plans_numpy([plan])
    if ok[0]:
        return outs[0]
    return _rans_decode1_py(data, p, n_out)


def rans_decode_batch(
    datas: Sequence[bytes], strict: bool = True
) -> List[Optional[bytes]]:
    """Decode many rANS 4x8 streams through the NumPy lockstep tier,
    rescuing any slice it rejects through the Python oracle.  With
    ``strict=False`` a slice whose oracle decode also fails comes back
    ``None`` instead of raising (the salvage shape)."""
    outs: List[Optional[bytes]] = [None] * len(datas)
    plans = []
    idxs = []
    for i, d in enumerate(datas):
        try:
            plans.append(parse_rans_plan(d))
            idxs.append(i)
        except CramError:
            if strict:
                raise
    got, ok = _decode_plans_numpy(plans)
    for k, i in enumerate(idxs):
        if ok[k]:
            outs[i] = got[k]
    for i, d in enumerate(datas):
        if outs[i] is None:
            try:
                outs[i] = rans_decode_py(d, 0)
            except Exception:
                if strict:
                    raise
    return outs


# ---------------------------------------------------------------------------
# rANS 4x8 encode (order-0 and order-1)
# ---------------------------------------------------------------------------


def _write_freq(f: int) -> bytes:
    if f >= 0x80:
        return bytes([0x80 | (f >> 8), f & 0xFF])
    return bytes([f])


def _norm_freqs(hist: List[int]) -> List[int]:
    """Scale a histogram to total exactly ``_TOTFREQ``; every occurring
    symbol keeps frequency ≥ 1 (a zero would make it undecodable)."""
    total = sum(hist)
    F = [0] * 256
    if total == 0:
        F[0] = _TOTFREQ
        return F
    acc = 0
    for s in range(256):
        if hist[s]:
            F[s] = max(1, (hist[s] * _TOTFREQ) // total)
            acc += F[s]
    # Settle the rounding drift: grow the most frequent symbol, or skim
    # the largest entries down (never below 1) when the min-clamps
    # overshot the budget.
    drift = _TOTFREQ - acc
    if drift >= 0:
        F[max(range(256), key=lambda s: F[s])] += drift
    else:
        while drift < 0:
            top = max(range(256), key=lambda s: F[s])
            take = min(-drift, F[top] - 1)
            if take <= 0:
                raise CramError("rANS frequency normalization failed")
            F[top] -= take
            drift += take
    return F


def _write_freq_table0(F: List[int]) -> bytes:
    """Order-0 table in the sym/RLE layout of :func:`_read_freq_table0`."""
    syms = [s for s in range(256) if F[s] > 0]
    out = bytearray([syms[0]])
    rle = 0
    for i, sym in enumerate(syms):
        out += _write_freq(F[sym])
        if rle > 0:
            rle -= 1
            continue
        nxt = syms[i + 1] if i + 1 < len(syms) else 0
        out.append(nxt)
        if nxt == sym + 1:
            run = 0
            k = i + 1
            while k + 1 < len(syms) and syms[k + 1] == syms[k] + 1:
                run += 1
                k += 1
            out.append(run)
            rle = run
    return bytes(out)


def _rans_enc_table(F: List[int]) -> Tuple[List[int], List[int]]:
    C = [0] * 257
    for i in range(256):
        C[i + 1] = C[i] + F[i]
    return F, C


def _rans_enc_step(R: int, f: int, c: int, emitted: bytearray) -> int:
    x_max = ((_RANS_L >> _TF_SHIFT) << 8) * f
    while R >= x_max:
        emitted.append(R & 0xFF)
        R >>= 8
    return ((R // f) << _TF_SHIFT) + c + (R % f)


def rans_encode(data: bytes, order: int = 0) -> bytes:
    """Encode ``data`` as one rANS 4x8 stream (CRAM 3.0 layout, the
    exact bitstream :func:`rans_decode` and the lanes kernel read).

    Symbols are pushed in reverse so the decoder pops them forward; the
    final four states land in the header.  Order-1 mirrors the decoder's
    quarter split: stream ``j`` owns quarter ``j`` (stream 3 plus the
    remainder tail), each byte conditioned on its predecessor, the four
    quarter-start bytes on context 0."""
    if order not in (0, 1):
        raise CramError(f"unknown rANS order {order}")
    n = len(data)
    if order == 0 or n == 0:
        hist = [0] * 256
        for b in data:
            hist[b] += 1
        F, C = _rans_enc_table(_norm_freqs(hist))
        table = _write_freq_table0(F)
        R = [_RANS_L] * 4
        emitted = bytearray()
        for i in range(n - 1, -1, -1):
            s = data[i]
            R[i & 3] = _rans_enc_step(R[i & 3], F[s], C[s], emitted)
        if order == 1 and n == 0:
            # An empty order-1 stream still carries an outer table with
            # the single context 0 so the shared parser accepts it.
            table = bytes([0]) + table + bytes([0])
        body = table + struct.pack("<4I", *R) + bytes(reversed(emitted))
        return bytes([order]) + struct.pack("<II", len(body), n) + body
    q4 = n >> 2
    idx = [0, q4, 2 * q4, 3 * q4]
    limits = [q4, q4, q4, n - 3 * q4]
    hists: Dict[int, List[int]] = {}
    for j in range(4):
        for step in range(limits[j]):
            pos = idx[j] + step
            ctx = data[pos - 1] if step > 0 else 0
            hists.setdefault(ctx, [0] * 256)[data[pos]] += 1
    tabs = {
        ctx: _rans_enc_table(_norm_freqs(h)) for ctx, h in hists.items()
    }
    # Outer table: contexts ascending, same RLE layout one level up.
    ctxs = sorted(tabs)
    table = bytearray([ctxs[0]])
    rle = 0
    for i, ctx in enumerate(ctxs):
        table += _write_freq_table0(tabs[ctx][0])
        if rle > 0:
            rle -= 1
            continue
        nxt = ctxs[i + 1] if i + 1 < len(ctxs) else 0
        table.append(nxt)
        if nxt == ctx + 1:
            run = 0
            k = i + 1
            while k + 1 < len(ctxs) and ctxs[k + 1] == ctxs[k] + 1:
                run += 1
                k += 1
            table.append(run)
            rle = run
    R = [_RANS_L] * 4
    emitted = bytearray()
    max_step = max(limits)
    for step in range(max_step - 1, -1, -1):
        for j in range(3, -1, -1):
            if step >= limits[j]:
                continue
            pos = idx[j] + step
            ctx = data[pos - 1] if step > 0 else 0
            F, C = tabs[ctx]
            s = data[pos]
            R[j] = _rans_enc_step(R[j], F[s], C[s], emitted)
    body = bytes(table) + struct.pack("<4I", *R) + bytes(reversed(emitted))
    return bytes([1]) + struct.pack("<II", len(body), n) + body


# ---------------------------------------------------------------------------
# Batched block decompression: the codec-tier seam
# ---------------------------------------------------------------------------


class RansTierStats:
    """Per-call tier accounting of :func:`decompress_batch`'s rANS leg
    (mirror of ``ops.flate.CodecTierStats`` for the third codec
    family)."""

    def __init__(self):
        self.lanes = 0          # slices decoded on the Pallas lanes tier
        self.host = 0           # slices decoded by the NumPy host tier
        self.tierdown_size = 0
        self.tierdown_vmem = 0
        self.tierdown_ctx = 0
        self.tierdown_format = 0
        self.tierdown_ok0 = 0

    def lanes_hit_rate(self) -> float:
        total = self.lanes + self.host
        return self.lanes / total if total else 0.0


#: Tier accounting of the most recent armed :func:`decompress_batch`
#: call (read by bench.py's CRAM leg).
LAST_RANS_STATS = RansTierStats()


def decompress_batch(
    blocks: Sequence[Tuple[int, bytes, int]],
    *,
    errors: str = "strict",
    stream=None,
    conf=None,
    use_lanes: Optional[bool] = None,
    interpret=None,
) -> List[Optional[bytes]]:
    """Decompress a container's blocks as one batch — the seam
    ``spec/cram.py`` block reading routes through instead of inflating
    one block at a time inline.

    ``blocks`` is a sequence of ``(method, payload, raw_size)`` triples.
    rANS 4x8 blocks ride the tier ladder: the Pallas lanes kernel when
    the gate is armed (``stream.policy.use_rans_lanes`` /
    ``ops.flate.rans_lanes_tier_enabled``) with per-slice tier-down —
    never per-launch — then the NumPy lockstep host tier, then the
    Python oracle.  Other methods decode on the host as before.

    ``errors="strict"`` raises on the first undecodable block;
    ``"salvage"`` returns ``None`` for that block (the caller quarantines
    its slice) and counts ``cram.codec.unsupported`` /
    ``cram.codec.corrupt``.  ``cram.rans.*`` counters move only when the
    lanes tier is armed — a disarmed stream stays metric-silent."""
    from ..utils.tracing import METRICS, span

    results: List[Optional[bytes]] = [None] * len(blocks)
    rans_idx = [
        i
        for i, (method, data, _raw) in enumerate(blocks)
        if method == METHOD_RANS and data
    ]
    rans_set = set(rans_idx)
    for i, (method, data, raw_size) in enumerate(blocks):
        if i in rans_set:
            continue
        try:
            results[i] = decompress(method, data, raw_size)
        except CramUnsupportedCodec:
            if errors != "salvage":
                raise
            METRICS.count("cram.codec.unsupported", 1)
        except Exception:
            if errors != "salvage":
                raise
            METRICS.count("cram.codec.corrupt", 1)
    if not rans_idx:
        return results
    if use_lanes is None:
        if stream is not None:
            use_lanes = bool(getattr(stream.policy, "use_rans_lanes", False))
        else:
            from ..ops import flate

            use_lanes = flate.rans_lanes_tier_enabled(conf)
    datas = [blocks[i][1] for i in rans_idx]
    outs: List[Optional[bytes]] = [None] * len(datas)
    with span("cram.stage.rans", category="stage"):
        if use_lanes:
            from ..ops.pallas import rans_lanes as _rl

            global LAST_RANS_STATS
            outs, stats = _rl.rans_lanes(datas, interpret=interpret)
            stats.host = sum(1 for o in outs if o is None)
            LAST_RANS_STATS = stats
            if stats.lanes:
                METRICS.count("cram.rans.lanes_slices", stats.lanes)
            if stats.host:
                METRICS.count("cram.rans.host_slices", stats.host)
            if stats.tierdown_size:
                METRICS.count("cram.rans.tierdown.size", stats.tierdown_size)
            if stats.tierdown_vmem:
                METRICS.count("cram.rans.tierdown.vmem", stats.tierdown_vmem)
            if stats.tierdown_ctx:
                METRICS.count("cram.rans.tierdown.ctx", stats.tierdown_ctx)
            if stats.tierdown_format:
                METRICS.count(
                    "cram.rans.tierdown.format", stats.tierdown_format
                )
            if stats.tierdown_ok0:
                METRICS.count("cram.rans.tierdown.ok0", stats.tierdown_ok0)
        pend = [k for k, o in enumerate(outs) if o is None]
        if pend:
            rescued = rans_decode_batch(
                [datas[k] for k in pend], strict=(errors != "salvage")
            )
            for k, out in zip(pend, rescued):
                outs[k] = out
                if out is None:
                    METRICS.count("cram.codec.corrupt", 1)
    for k, i in enumerate(rans_idx):
        results[i] = outs[k]
    return results


# ---------------------------------------------------------------------------
# Encoding family
# ---------------------------------------------------------------------------

ENC_NULL = 0
ENC_EXTERNAL = 1
ENC_GOLOMB = 2
ENC_HUFFMAN = 3
ENC_BYTE_ARRAY_LEN = 4
ENC_BYTE_ARRAY_STOP = 5
ENC_BETA = 6
ENC_SUBEXP = 7
ENC_GOLOMB_RICE = 8
ENC_GAMMA = 9


class ExternalStream:
    """One external block's payload with a read cursor."""

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def read_byte(self) -> int:
        b = self.data[self.pos]
        self.pos += 1
        return b

    def read_bytes(self, n: int) -> bytes:
        b = self.data[self.pos : self.pos + n]
        if len(b) != n:
            raise CramError("external stream exhausted")
        self.pos += n
        return b

    def read_itf8(self) -> int:
        v, self.pos = read_itf8(self.data, self.pos)
        return v

    def read_until(self, stop: int) -> bytes:
        i = self.data.index(bytes([stop]), self.pos)
        out = self.data[self.pos : i]
        self.pos = i + 1
        return out


class DecodeContext:
    """Core bit stream + external streams for one slice."""

    def __init__(self, core: bytes, external: Dict[int, bytes]):
        self.core = BitReader(core)
        self.external = {k: ExternalStream(v) for k, v in external.items()}

    def stream(self, cid: int) -> ExternalStream:
        try:
            return self.external[cid]
        except KeyError:
            raise CramError(f"missing external block {cid}")


def parse_encoding(buf: bytes, pos: int) -> Tuple["Encoding", int]:
    codec, pos = read_itf8(buf, pos)
    nparams, pos = read_itf8(buf, pos)
    params = buf[pos : pos + nparams]
    pos += nparams
    return Encoding(codec, bytes(params)), pos


class Encoding:
    """One parsed encoding: decodes ints or byte arrays from a context."""

    def __init__(self, codec: int, params: bytes):
        self.codec = codec
        self.params = params
        self._parse()

    def _parse(self) -> None:
        p = self.params
        c = self.codec
        if c == ENC_EXTERNAL:
            self.content_id, _ = read_itf8(p, 0)
        elif c == ENC_HUFFMAN:
            n, q = read_itf8(p, 0)
            self.symbols = []
            for _ in range(n):
                v, q = read_itf8(p, q)
                self.symbols.append(v)
            m, q = read_itf8(p, q)
            self.lengths = []
            for _ in range(m):
                v, q = read_itf8(p, q)
                self.lengths.append(v)
            self._build_huffman()
        elif c == ENC_BYTE_ARRAY_LEN:
            self.len_enc, q = parse_encoding(p, 0)
            self.val_enc, _ = parse_encoding(p, q)
        elif c == ENC_BYTE_ARRAY_STOP:
            self.stop = p[0]
            self.content_id, _ = read_itf8(p, 1)
        elif c == ENC_BETA:
            self.offset, q = read_itf8(p, 0)
            self.nbits, _ = read_itf8(p, q)
        elif c == ENC_SUBEXP:
            self.offset, q = read_itf8(p, 0)
            self.k, _ = read_itf8(p, q)
        elif c == ENC_GAMMA:
            self.offset, _ = read_itf8(p, 0)
        elif c == ENC_GOLOMB or c == ENC_GOLOMB_RICE:
            self.offset, q = read_itf8(p, 0)
            self.m, _ = read_itf8(p, q)
        elif c == ENC_NULL:
            pass
        else:
            raise CramError(f"unsupported encoding id {c}")

    def _build_huffman(self) -> None:
        # canonical codes: sort by (length, symbol)
        pairs = sorted(zip(self.lengths, self.symbols))
        self._codes: Dict[Tuple[int, int], int] = {}
        code = 0
        prev_len = 0
        for ln, sym in pairs:
            code <<= ln - prev_len
            prev_len = ln
            self._codes[(ln, code)] = sym
            code += 1
        self._zero_bit = len(pairs) == 1 and pairs[0][0] == 0
        self._single = pairs[0][1] if self._zero_bit else None
        self._max_len = max(self.lengths) if self.lengths else 0

    # -- int decode ----------------------------------------------------------

    def read_int(self, ctx: DecodeContext) -> int:
        c = self.codec
        if c == ENC_EXTERNAL:
            return ctx.stream(self.content_id).read_itf8()
        if c == ENC_HUFFMAN:
            if self._zero_bit:
                return self._single  # type: ignore[return-value]
            code = 0
            ln = 0
            while ln <= self._max_len:
                code = (code << 1) | ctx.core.read_bit()
                ln += 1
                sym = self._codes.get((ln, code))
                if sym is not None:
                    return sym
            raise CramError("bad huffman code")
        if c == ENC_BETA:
            return ctx.core.read_bits(self.nbits) - self.offset
        if c == ENC_GAMMA:
            n = 0
            while ctx.core.read_bit() == 0:
                n += 1
            v = 1
            for _ in range(n):
                v = (v << 1) | ctx.core.read_bit()
            return v - self.offset
        if c == ENC_SUBEXP:
            n = 0
            while ctx.core.read_bit() == 1:
                n += 1
            if n == 0:
                v = ctx.core.read_bits(self.k)
            else:
                v = (1 << (self.k + n - 1)) | ctx.core.read_bits(
                    self.k + n - 1
                )
            return v - self.offset
        raise CramError(f"encoding {c} cannot decode ints")

    # -- byte decode ---------------------------------------------------------

    def read_byte(self, ctx: DecodeContext) -> int:
        c = self.codec
        if c == ENC_EXTERNAL:
            return ctx.stream(self.content_id).read_byte()
        if c in (ENC_HUFFMAN, ENC_BETA, ENC_GAMMA, ENC_SUBEXP):
            return self.read_int(ctx)
        raise CramError(f"encoding {c} cannot decode bytes")

    def read_byte_run(self, ctx: DecodeContext, n: int) -> bytes:
        """``n`` consecutive bytes of this series in one call.

        The hot byte series (QS qualities, BA bases) are EXTERNAL in
        practice — one stream slice instead of n Python calls; a
        zero-bit Huffman constant is one repeat.  Other codecs keep the
        per-byte loop (bit-level state)."""
        if n <= 0:
            return b""
        c = self.codec
        if c == ENC_EXTERNAL:
            return ctx.stream(self.content_id).read_bytes(n)
        if c == ENC_HUFFMAN and self._zero_bit:
            return bytes([self._single]) * n  # type: ignore[list-item]
        return bytes(self.read_byte(ctx) for _ in range(n))

    def read_bytes(self, ctx: DecodeContext, n: Optional[int] = None) -> bytes:
        c = self.codec
        if c == ENC_BYTE_ARRAY_STOP:
            return ctx.stream(self.content_id).read_until(self.stop)
        if c == ENC_BYTE_ARRAY_LEN:
            ln = self.len_enc.read_int(ctx)
            if self.val_enc.codec == ENC_EXTERNAL:
                return ctx.stream(self.val_enc.content_id).read_bytes(ln)
            return bytes(self.val_enc.read_byte(ctx) for _ in range(ln))
        if c == ENC_EXTERNAL:
            if n is None:
                raise CramError("EXTERNAL byte array needs explicit length")
            return ctx.stream(self.content_id).read_bytes(n)
        raise CramError(f"encoding {c} cannot decode byte arrays")


# ---------------------------------------------------------------------------
# Encoding builders (write side)
# ---------------------------------------------------------------------------


def encoding_external(content_id: int) -> bytes:
    from .cram import write_itf8

    params = write_itf8(content_id)
    return write_itf8(ENC_EXTERNAL) + write_itf8(len(params)) + params


def encoding_byte_array_stop(stop: int, content_id: int) -> bytes:
    from .cram import write_itf8

    params = bytes([stop]) + write_itf8(content_id)
    return write_itf8(ENC_BYTE_ARRAY_STOP) + write_itf8(len(params)) + params


def encoding_byte_array_len_external(len_id: int, val_id: int) -> bytes:
    from .cram import write_itf8

    nested_len = encoding_external(len_id)
    nested_val = encoding_external(val_id)
    params = nested_len + nested_val
    return write_itf8(ENC_BYTE_ARRAY_LEN) + write_itf8(len(params)) + params


