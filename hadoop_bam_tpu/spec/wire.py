"""Header-free wire format for shuffling variants between processes.

The VariantContextCodec role (VariantContextCodec.java:47-249): BCF cannot
encode a headerless record and htsjdk's VCFWriter refuses to write without a
header (VariantContextWritable.java:44-53), so the reference defines its own
wire format for moving variants across the MapReduce shuffle.  This is the
TPU-framework equivalent for moving variants between hosts around the
all-to-all: chrom/start/end/id/alleles/qual (signaling-NaN missing =
0x7F800001)/filters/INFO text, with genotype data kept **unparsed** — either
VCF text or the raw BCF indiv block (the Lazy*GenotypesContext stance).
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

from .bcf import BcfHeader, BcfVariant, LazyBcfGenotypes, FLOAT_MISSING_BITS
from .vcf import VariantContext

_GT_NONE = 0  # no genotype data
_GT_VCF_TEXT = 1  # FORMAT+samples as VCF text (LazyVCFGenotypesContext)
_GT_BCF_RAW = 2  # undecoded BCF indiv block (LazyBCFGenotypesContext)


def _put_str(out: bytearray, s: str) -> None:
    raw = s.encode()
    out.extend(struct.pack("<I", len(raw)))
    out.extend(raw)


def _get_str(buf, p: int) -> Tuple[str, int]:
    (n,) = struct.unpack_from("<I", buf, p)
    p += 4
    return bytes(buf[p : p + n]).decode(), p + n


def encode_variant(v: VariantContext) -> bytes:
    out = bytearray()
    _put_str(out, v.chrom)
    out.extend(struct.pack("<ii", v.pos, v.end))
    _put_str(out, v.id)
    alleles = [v.ref] + list(v.alts)
    out.extend(struct.pack("<I", len(alleles)))
    for a in alleles:
        _put_str(out, a)
    if v.qual is None:
        out.extend(struct.pack("<I", FLOAT_MISSING_BITS))
    else:
        out.extend(struct.pack("<f", v.qual))
    out.extend(struct.pack("<I", len(v.filters)))
    for f in v.filters:
        _put_str(out, f)
    _put_str(out, v.info)
    lazy = getattr(v, "_lazy", None)
    wire = getattr(v, "_wire_bcf_genotypes", None)
    if isinstance(v, BcfVariant) and lazy is not None:
        out.append(_GT_BCF_RAW)
        out.extend(struct.pack("<II", lazy.n_fmt, lazy.n_sample))
        out.extend(struct.pack("<I", len(lazy.raw)))
        out.extend(lazy.raw)
    elif wire is not None:
        # Decoded without a header and never reattached: the raw indiv block
        # must keep travelling on a re-encode (multi-hop relay).
        n_fmt, n_sample, raw = wire
        out.append(_GT_BCF_RAW)
        out.extend(struct.pack("<II", n_fmt, n_sample))
        out.extend(struct.pack("<I", len(raw)))
        out.extend(raw)
    elif v.genotypes_raw:
        out.append(_GT_VCF_TEXT)
        _put_str(out, v.genotypes_raw)
    else:
        out.append(_GT_NONE)
    return bytes(out)


def decode_variant(
    buf, p: int = 0, bcf_header: Optional[BcfHeader] = None
) -> Tuple[VariantContext, int]:
    """Decode one variant.  ``bcf_header`` plays the HeaderDataCache role
    (VCFRecordWriter.java:141-149): it must be supplied before BCF-raw
    genotypes can materialise; the raw bytes travel regardless."""
    chrom, p = _get_str(buf, p)
    pos, end = struct.unpack_from("<ii", buf, p)
    p += 8
    vid, p = _get_str(buf, p)
    (n_alleles,) = struct.unpack_from("<I", buf, p)
    p += 4
    alleles = []
    for _ in range(n_alleles):
        a, p = _get_str(buf, p)
        alleles.append(a)
    (qual_bits,) = struct.unpack_from("<I", buf, p)
    qual = (
        None
        if qual_bits == FLOAT_MISSING_BITS
        else struct.unpack_from("<f", buf, p)[0]
    )
    p += 4
    (n_filt,) = struct.unpack_from("<I", buf, p)
    p += 4
    filters = []
    for _ in range(n_filt):
        f, p = _get_str(buf, p)
        filters.append(f)
    info, p = _get_str(buf, p)
    kind = buf[p]
    p += 1
    common = dict(
        chrom=chrom,
        pos=pos,
        id=vid,
        ref=alleles[0] if alleles else "N",
        alts=alleles[1:],
        qual=qual,
        filters=filters,
        info=info,
    )
    if kind == _GT_BCF_RAW:
        n_fmt, n_sample = struct.unpack_from("<II", buf, p)
        p += 8
        (n_raw,) = struct.unpack_from("<I", buf, p)
        p += 4
        raw = bytes(buf[p : p + n_raw])
        p += n_raw
        lazy = (
            LazyBcfGenotypes(bcf_header, n_fmt, n_sample, raw)
            if bcf_header is not None
            else None
        )
        v: VariantContext = BcfVariant(genotypes_raw="", lazy=lazy, **common)
        if lazy is None:
            v._wire_bcf_genotypes = (n_fmt, n_sample, raw)  # reattach later
        return v, p
    gt = ""
    if kind == _GT_VCF_TEXT:
        gt, p = _get_str(buf, p)
    return VariantContext(genotypes_raw=gt, **common), p


def reattach_genotypes(v: VariantContext, bcf_header: BcfHeader) -> None:
    """Late header attachment for variants decoded without one
    (LazyParsingGenotypesContext.HeaderDataCache semantics)."""
    wire = getattr(v, "_wire_bcf_genotypes", None)
    if wire is not None:
        n_fmt, n_sample, raw = wire
        v._lazy = LazyBcfGenotypes(bcf_header, n_fmt, n_sample, raw)
        del v._wire_bcf_genotypes
