"""BAM file layout: header, reference dictionary, alignment record codec.

Golden-oracle implementation of the BAM binary format (the role htsjdk's
``BAMRecordCodec``/``BAMFileReader`` play below reference L0), plus the
NumPy structure-of-arrays batch decode that defines the device tensor layout
used by ops/ (SURVEY.md §7 stage 4).

Key functions reproduce reference semantics exactly:
- ``alignment_key`` == BAMRecordReader.getKey/getKey0
  (BAMRecordReader.java:81-121): ``refIdx << 32 | pos0`` for mapped records,
  ``Integer.MAX_VALUE << 32 | murmur3(raw record bytes)`` for unmapped ones —
  including Java's sign extension of the 32-bit hash into the low word.
- The "lazy" stance of LazyBAMRecordFactory (LazyBAMRecordFactory.java:53-111):
  records decode without a header; names/cigars/seq/qual/tags stay as raw byte
  slices until asked for (the ragged sideband of the SoA layout).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import BinaryIO, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..utils.murmur3 import murmurhash3_int32

MAGIC = b"BAM\x01"

# SEQ 4-bit code → base character ("=ACMGRSVTWYHKDBN", SAM spec table).
SEQ_DECODE = "=ACMGRSVTWYHKDBN"
_SEQ_ENCODE = {c: i for i, c in enumerate(SEQ_DECODE)}
# Byte-wise nibble table for the vectorized encode path: byte b maps to
# _SEQ_ENCODE.get(chr(b).upper(), 15) (identical for all latin-1 bytes).
_SEQ_NIB_TABLE = bytes(
    _SEQ_ENCODE.get(chr(_b).upper(), 15) for _b in range(256)
)
CIGAR_OPS = "MIDNSHP=X"
_CIGAR_ENCODE = {c: i for i, c in enumerate(CIGAR_OPS)}

FLAG_PAIRED = 0x1
FLAG_PROPER_PAIR = 0x2
FLAG_UNMAPPED = 0x4
FLAG_MATE_UNMAPPED = 0x8
FLAG_REVERSE = 0x10
FLAG_MATE_REVERSE = 0x20
FLAG_FIRST_OF_PAIR = 0x40
FLAG_SECOND_OF_PAIR = 0x80
FLAG_SECONDARY = 0x100
FLAG_FAIL_QC = 0x200
FLAG_DUPLICATE = 0x400
FLAG_SUPPLEMENTARY = 0x800

INT_MAX = 0x7FFFFFFF  # Java Integer.MAX_VALUE, the unmapped refIdx sentinel

# Fixed 32-byte prefix of every alignment record, after the u32 block_size:
# refID, pos, l_read_name, mapq, bin, n_cigar_op, flag, l_seq,
# next_refID, next_pos, tlen.
_FIXED = struct.Struct("<iiBBHHHIiii")


class BamError(IOError):
    pass


@dataclass
class BamHeader:
    """Parsed BAM header: SAM text + binary reference dictionary."""

    text: str
    refs: List[Tuple[str, int]] = field(default_factory=list)

    @property
    def n_refs(self) -> int:
        return len(self.refs)

    def ref_name(self, refid: int) -> str:
        return "*" if refid < 0 else self.refs[refid][0]

    def ref_index(self, name: str) -> int:
        if name == "*":
            return -1
        for i, (n, _) in enumerate(self.refs):
            if n == name:
                return i
        raise KeyError(name)

    def sort_order(self) -> str:
        for line in self.text.split("\n"):
            if line.startswith("@HD"):
                for f in line.split("\t"):
                    if f.startswith("SO:"):
                        return f[3:]
        return "unknown"

    def grouping(self) -> str:
        """The @HD GO: field (record grouping: none/query/reference), or
        "none" — the SAM-spec default — when absent."""
        for line in self.text.split("\n"):
            if line.startswith("@HD"):
                for f in line.split("\t"):
                    if f.startswith("GO:"):
                        return f[3:]
        return "none"

    def with_sort_order(
        self, so: str, grouping: Optional[str] = None
    ) -> "BamHeader":
        """Rewritten @HD SO: field (util/GetSortedBAMHeader.java:36-57
        semantics: force the header's sort order before a sorted write).

        The header claims what the write path actually produced — never
        an unconditional "coordinate" (the pipelines thread their real
        sort order here).  ``grouping`` additionally rewrites the GO:
        field (e.g. ``GO:query`` for name-grouped-but-not-sorted
        output); a stale GO: is always stripped when SO: is rewritten,
        since a sorted stream's grouping claim no longer holds."""
        lines = self.text.split("\n")
        hd_seen = False
        for i, line in enumerate(lines):
            if line.startswith("@HD"):
                hd_seen = True
                fields = [
                    f
                    for f in line.split("\t")
                    if not f.startswith(("SO:", "GO:"))
                ]
                fields.append(f"SO:{so}")
                if grouping is not None:
                    fields.append(f"GO:{grouping}")
                lines[i] = "\t".join(fields)
        if not hd_seen:
            hd = f"@HD\tVN:1.6\tSO:{so}"
            if grouping is not None:
                hd += f"\tGO:{grouping}"
            lines.insert(0, hd)
        return BamHeader("\n".join(lines), list(self.refs))

    def encode(self) -> bytes:
        """Binary header block: magic, l_text, text, n_ref, ref dict
        (the bytes BAMRecordWriter.writeHeader emits,
        BAMRecordWriter.java:152-167)."""
        text = self.text.encode()
        out = bytearray()
        out += MAGIC
        out += struct.pack("<i", len(text))
        out += text
        out += struct.pack("<i", len(self.refs))
        for name, length in self.refs:
            nb = name.encode() + b"\x00"
            out += struct.pack("<i", len(nb))
            out += nb
            out += struct.pack("<i", length)
        return bytes(out)

    @staticmethod
    def decode(buf: bytes, pos: int = 0) -> Tuple["BamHeader", int]:
        """Parse the header block; returns (header, offset_after_header)."""
        if buf[pos : pos + 4] != MAGIC:
            raise BamError("missing BAM magic")
        (l_text,) = struct.unpack_from("<i", buf, pos + 4)
        p = pos + 8
        text = buf[p : p + l_text].split(b"\x00", 1)[0].decode()
        p += l_text
        (n_ref,) = struct.unpack_from("<i", buf, p)
        p += 4
        refs: List[Tuple[str, int]] = []
        for _ in range(n_ref):
            (l_name,) = struct.unpack_from("<i", buf, p)
            p += 4
            name = buf[p : p + l_name - 1].decode()
            p += l_name
            (l_ref,) = struct.unpack_from("<i", buf, p)
            p += 4
            refs.append((name, l_ref))
        return BamHeader(text, refs), p


def header_from_text(text: str) -> "BamHeader":
    """Header from SAM text alone: the reference dictionary is rebuilt from
    the ``@SQ`` lines (SAM/CRAM header readers share this)."""
    refs: List[Tuple[str, int]] = []
    for line in text.split("\n"):
        if line.startswith("@SQ"):
            name: Optional[str] = None
            ln = 0
            for f in line.split("\t")[1:]:
                if f.startswith("SN:"):
                    name = f[3:]
                elif f.startswith("LN:"):
                    ln = int(f[3:])
            refs.append((name or "?", ln))
    return BamHeader(text, refs)


@dataclass
class BamRecord:
    """One alignment; fixed fields decoded, variable tails as raw bytes.

    ``raw`` holds the record body (everything after block_size), so the
    record can be re-encoded or hashed without any header — the
    LazyBAMRecordFactory stance (LazyBAMRecordFactory.java:31-51).
    """

    refid: int
    pos: int  # 0-based leftmost, -1 if unplaced
    mapq: int
    bin: int
    flag: int
    next_refid: int
    next_pos: int
    tlen: int
    raw: bytes  # full record body (fixed prefix + tails), header-free

    @property
    def l_read_name(self) -> int:
        return self.raw[8]

    @property
    def n_cigar_op(self) -> int:
        return struct.unpack_from("<H", self.raw, 12)[0]

    @property
    def l_seq(self) -> int:
        return struct.unpack_from("<I", self.raw, 16)[0]

    @property
    def read_name(self) -> str:
        return self.raw[32 : 32 + self.l_read_name - 1].decode()

    @property
    def cigar_raw(self) -> np.ndarray:
        off = 32 + self.l_read_name
        return np.frombuffer(
            self.raw, dtype="<u4", count=self.n_cigar_op, offset=off
        )

    @property
    def cigar(self) -> List[Tuple[int, str]]:
        return [
            (int(c) >> 4, CIGAR_OPS[int(c) & 0xF]) for c in self.cigar_raw
        ]

    def cigar_string(self) -> str:
        ops = self.cigar
        return "*" if not ops else "".join(f"{n}{op}" for n, op in ops)

    @property
    def seq(self) -> str:
        l_seq = self.l_seq
        if l_seq == 0:
            return "*"
        off = 32 + self.l_read_name + 4 * self.n_cigar_op
        packed = self.raw[off : off + (l_seq + 1) // 2]
        out = []
        for i in range(l_seq):
            b = packed[i // 2]
            out.append(SEQ_DECODE[(b >> 4) if i % 2 == 0 else (b & 0xF)])
        return "".join(out)

    @property
    def qual(self) -> bytes:
        l_seq = self.l_seq
        off = 32 + self.l_read_name + 4 * self.n_cigar_op + (l_seq + 1) // 2
        return self.raw[off : off + l_seq]

    @property
    def tags_raw(self) -> bytes:
        l_seq = self.l_seq
        off = 32 + self.l_read_name + 4 * self.n_cigar_op + (l_seq + 1) // 2 + l_seq
        return self.raw[off:]

    @property
    def is_unmapped(self) -> bool:
        return bool(self.flag & FLAG_UNMAPPED)

    @property
    def is_duplicate(self) -> bool:
        """PCR/optical duplicate flag (0x400) — set by the dedup
        subsystem's write-time patch, never by the decoder."""
        return bool(self.flag & FLAG_DUPLICATE)

    @property
    def alignment_start(self) -> int:
        """1-based leftmost coordinate (htsjdk getAlignmentStart), 0 if unplaced."""
        return self.pos + 1

    def reference_length(self) -> int:
        """Span on the reference from the CIGAR (for BAI bin computation)."""
        span = 0
        for n, op in self.cigar:
            if op in "MDN=X":
                span += n
        return span

    def encode(self) -> bytes:
        return struct.pack("<I", len(self.raw)) + self.raw


def decode_record(buf: bytes, pos: int = 0) -> Tuple[BamRecord, int]:
    """Decode one record at ``pos``; returns (record, offset_after)."""
    if pos + 4 > len(buf):
        raise BamError("truncated record: no block_size")
    (block_size,) = struct.unpack_from("<I", buf, pos)
    body = buf[pos + 4 : pos + 4 + block_size]
    if len(body) != block_size:
        raise BamError("truncated record body")
    (refid, p, _lname, mapq, bin_, _ncig, flag, _lseq, nrefid, npos, tlen) = (
        _FIXED.unpack_from(body, 0)
    )
    rec = BamRecord(refid, p, mapq, bin_, flag, nrefid, npos, tlen, bytes(body))
    return rec, pos + 4 + block_size


def iter_records(buf: bytes, pos: int = 0, end: Optional[int] = None) -> Iterator[BamRecord]:
    end = len(buf) if end is None else end
    while pos < end:
        rec, pos = decode_record(buf, pos)
        yield rec


def build_record(
    name: str,
    refid: int,
    pos: int,
    mapq: int,
    flag: int,
    cigar: Sequence[Tuple[int, str]],
    seq: str,
    qual: Union[bytes, str],
    next_refid: int = -1,
    next_pos: int = -1,
    tlen: int = 0,
    tags: bytes = b"",
) -> BamRecord:
    """Construct a record from logical fields (the encode path)."""
    name_b = name.encode() + b"\x00"
    if len(name_b) > 255:
        raise BamError("read name too long")
    cigar_b = b"".join(
        struct.pack("<I", (n << 4) | _CIGAR_ENCODE[op]) for n, op in cigar
    )
    if seq == "*":
        l_seq = 0
        seq_b = b""
    else:
        l_seq = len(seq)
        try:
            # Byte-wise fast path: one translate + one vectorized pack
            # (equivalent to the per-char dict walk for every latin-1
            # string — upper() of a latin-1 char never lands in the
            # nibble alphabet unless the byte-wise upper does too).
            nib = seq.encode("latin-1").translate(_SEQ_NIB_TABLE)
            if l_seq % 2:
                nib += b"\x00"
            arr = np.frombuffer(nib, dtype=np.uint8)
            seq_b = ((arr[0::2] << 4) | arr[1::2]).astype(np.uint8).tobytes()
        except UnicodeEncodeError:
            nibbles = [_SEQ_ENCODE.get(c.upper(), 15) for c in seq]
            if l_seq % 2:
                nibbles.append(0)
            seq_b = bytes(
                (nibbles[i] << 4) | nibbles[i + 1]
                for i in range(0, len(nibbles), 2)
            )
    if isinstance(qual, str):
        qual_b = (
            b"\xff" * l_seq if qual == "*" else bytes(ord(c) - 33 for c in qual)
        )
    else:
        qual_b = qual if qual else b"\xff" * l_seq
    # htsjdk ignores the CIGAR for flag-unmapped reads: their alignment end
    # equals their start, so the bin covers a single base.
    span = 1 if (flag & FLAG_UNMAPPED) else max(1, _ref_span(cigar))
    bin_ = reg2bin(pos, pos + span) if pos >= 0 else 4680
    body = (
        _FIXED.pack(
            refid,
            pos,
            len(name_b),
            mapq,
            bin_,
            len(cigar),
            flag,
            l_seq,
            next_refid,
            next_pos,
            tlen,
        )
        + name_b
        + cigar_b
        + seq_b
        + qual_b
        + tags
    )
    return BamRecord(
        refid, pos, mapq, bin_, flag, next_refid, next_pos, tlen, body
    )


def _ref_span(cigar: Sequence[Tuple[int, str]]) -> int:
    return sum(n for n, op in cigar if op in "MDN=X")


def reg2bin(beg: int, end: int) -> int:
    """UCSC binning scheme (SAM spec §5.3)."""
    end -= 1
    if beg >> 14 == end >> 14:
        return ((1 << 15) - 1) // 7 + (beg >> 14)
    if beg >> 17 == end >> 17:
        return ((1 << 12) - 1) // 7 + (beg >> 17)
    if beg >> 20 == end >> 20:
        return ((1 << 9) - 1) // 7 + (beg >> 20)
    if beg >> 23 == end >> 23:
        return ((1 << 6) - 1) // 7 + (beg >> 23)
    if beg >> 26 == end >> 26:
        return ((1 << 3) - 1) // 7 + (beg >> 26)
    return 0


def read_header_stream(reader) -> "BamHeader":
    """Parse the BAM header from a BgzfReader-like stream, leaving it
    positioned at the first record (the shared header-skip walk used by the
    guesser, the index builders, and the input format)."""
    if reader.read_fully(4) != MAGIC:
        raise BamError("missing BAM magic")
    (l_text,) = struct.unpack("<i", reader.read_fully(4))
    if l_text < 0:
        raise BamError("negative l_text in BAM header")
    text = reader.read_fully(l_text).split(b"\x00", 1)[0].decode()
    (n_ref,) = struct.unpack("<i", reader.read_fully(4))
    if n_ref < 0:
        raise BamError("negative n_ref in BAM header")
    refs: List[Tuple[str, int]] = []
    for _ in range(n_ref):
        (l_name,) = struct.unpack("<i", reader.read_fully(4))
        if l_name < 1:
            raise BamError("invalid reference name length")
        name = reader.read_fully(l_name)[:-1].decode()
        (l_ref,) = struct.unpack("<i", reader.read_fully(4))
        refs.append((name, l_ref))
    return BamHeader(text, refs)


# ---------------------------------------------------------------------------
# Sort keys (reference BAMRecordReader.java:81-121, exact semantics)
# ---------------------------------------------------------------------------


def key0(refidx: int, pos0: int) -> int:
    """``(long)refIdx << 32 | alignmentStart0`` with Java int→long sign
    extension of both operands (BAMRecordReader.java:119-121)."""
    lo = pos0 & 0xFFFFFFFFFFFFFFFF if pos0 < 0 else pos0
    v = ((refidx << 32) | lo) & 0xFFFFFFFFFFFFFFFF
    return v - (1 << 64) if v >= 1 << 63 else v


def alignment_key(rec: BamRecord) -> int:
    """The shuffle/sort key.  Mapped: ``refIdx<<32 | pos0``.  Unmapped (or
    negative refIdx/start): ``INT_MAX<<32 | (int)murmur3(...)`` so they sort
    last but spread over partitions (BAMRecordReader.java:81-117).  The hash
    input is the record's *variable* section only — htsjdk's
    ``getVariableBinaryRepresentation()`` is the bytes after the 32-byte fixed
    prefix (BAMRecordReader.java:100-102)."""
    if not (rec.is_unmapped or rec.refid < 0 or rec.alignment_start < 0):
        return key0(rec.refid, rec.pos)
    return key0(INT_MAX, murmurhash3_int32(rec.raw[32:], 0))


# ---------------------------------------------------------------------------
# Structure-of-arrays batch decode: the device tensor layout
# ---------------------------------------------------------------------------

# Column order of the fixed-field SoA matrix produced by soa_decode.
SOA_FIELDS = (
    "refid",
    "pos",
    "flag",
    "mapq",
    "bin",
    "n_cigar_op",
    "l_read_name",
    "l_seq",
    "next_refid",
    "next_pos",
    "tlen",
    "rec_off",  # byte offset of the record body in the ragged sideband
    "rec_len",  # body length
)
SOA_NCOLS = len(SOA_FIELDS)


def record_offsets(buf: np.ndarray, pos: int = 0, end: Optional[int] = None) -> np.ndarray:
    """Offsets of each record's block_size word: the record-boundary chain.

    This is the serial prefix walk the device kernels re-derive with a scan
    (SURVEY.md §7 stage 4); kept here as the oracle.
    """
    end = len(buf) if end is None else end
    offs = []
    while pos + 4 <= end:
        block_size = (
            int(buf[pos])
            | (int(buf[pos + 1]) << 8)
            | (int(buf[pos + 2]) << 16)
            | (int(buf[pos + 3]) << 24)
        )
        offs.append(pos)
        pos += 4 + block_size
    if pos != end:
        raise BamError(f"record chain misaligned: ended at {pos} != {end}")
    return np.asarray(offs, dtype=np.int64)


def soa_decode(
    data: bytes, offsets: np.ndarray, fields: Optional[Sequence[str]] = None
) -> dict:
    """Vectorized fixed-field gather → SoA dict of int32/int64 arrays.

    ``data`` is the uncompressed BAM record stream, ``offsets`` the
    block_size-word offsets.  Variable-length tails stay in ``data`` (the
    ragged sideband), addressed by ``rec_off``/``rec_len``.

    ``fields`` restricts decoding to a subset of :data:`SOA_FIELDS` — each
    column is several fancy-index gathers over the whole stream, so hot
    paths that only need keys + record extents skip the rest.
    """
    a = (
        data
        if isinstance(data, np.ndarray)
        else np.frombuffer(data, dtype=np.uint8)
    )
    offs = offsets.astype(np.int64)

    def u32(at: np.ndarray) -> np.ndarray:
        return (
            a[at].astype(np.uint32)
            | (a[at + 1].astype(np.uint32) << 8)
            | (a[at + 2].astype(np.uint32) << 16)
            | (a[at + 3].astype(np.uint32) << 24)
        )

    def i32(at: np.ndarray) -> np.ndarray:
        return u32(at).astype(np.int32)

    def u16(at: np.ndarray) -> np.ndarray:
        return (
            a[at].astype(np.uint16) | (a[at + 1].astype(np.uint16) << 8)
        ).astype(np.int32)

    body = offs + 4
    cols = {
        "refid": lambda: i32(body + 0),
        "pos": lambda: i32(body + 4),
        "l_read_name": lambda: a[body + 8].astype(np.int32),
        "mapq": lambda: a[body + 9].astype(np.int32),
        "bin": lambda: u16(body + 10),
        "n_cigar_op": lambda: u16(body + 12),
        "flag": lambda: u16(body + 14),
        "l_seq": lambda: i32(body + 16),
        "next_refid": lambda: i32(body + 20),
        "next_pos": lambda: i32(body + 24),
        "tlen": lambda: i32(body + 28),
        "rec_off": lambda: body,
        "rec_len": lambda: u32(offs).astype(np.int64),
    }
    want = SOA_FIELDS if fields is None else tuple(fields)
    return {k: cols[k]() for k in want}


def soa_keys(soa: dict, data: bytes) -> np.ndarray:
    """int64 sort keys for a decoded SoA batch (oracle path).

    Mapped rows use the closed-form key; unmapped rows hash their raw bytes
    (host loop — the batched C++/device variants must match this)."""
    refid = soa["refid"].astype(np.int64)
    pos = soa["pos"].astype(np.int64)
    flag = soa["flag"]
    # No masking of pos: Java ORs the sign-extended 32-bit int into the long
    # (BAMRecordReader.java:119-121), so pos0 == -1 floods the high word.
    keys = (refid << np.int64(32)) | pos
    unmapped = (
        ((flag & FLAG_UNMAPPED) != 0) | (refid < 0) | (pos + 1 < 0)
    )
    if np.any(unmapped):
        idx = np.nonzero(unmapped)[0]
        for i in idx:
            off = int(soa["rec_off"][i])
            ln = int(soa["rec_len"][i])
            blob = data[off + 32 : off + ln]
            if isinstance(blob, np.ndarray):
                blob = blob.tobytes()
            keys[i] = key0(INT_MAX, murmurhash3_int32(blob, 0))
    return keys


# ---------------------------------------------------------------------------
# Whole-file helpers
# ---------------------------------------------------------------------------


def read_bam(path_or_bytes: Union[str, bytes]) -> Tuple[BamHeader, List[BamRecord]]:
    from . import bgzf

    if isinstance(path_or_bytes, str):
        with open(path_or_bytes, "rb") as f:
            raw = f.read()
    else:
        raw = path_or_bytes
    data = bgzf.decompress_all(raw)
    header, p = BamHeader.decode(data)
    return header, list(iter_records(data, p))


def write_bam(
    stream: BinaryIO,
    header: BamHeader,
    records: Iterator[BamRecord],
    level: int = 6,
    append_terminator: bool = True,
    write_header: bool = True,
) -> None:
    from . import bgzf

    w = bgzf.BgzfWriter(stream, level=level, append_terminator=append_terminator)
    if write_header:
        w.write(header.encode())
    for rec in records:
        w.write(rec.encode())
    w.close()
