"""Index formats: `.splitting-bai`, `.bai`, `.tbi` (tabix), `.bgzfi`.

These are the persistent split-planning artifacts of the reference
(SURVEY.md §2.1/§2.2); they double as resumable metadata — built once, reused
every job (SplittingBAMIndexer.java:64-70).

- SplittingBai: big-endian u64 virtual offsets of every g-th alignment,
  terminated by ``fileSize << 16`` (SplittingBAMIndexer.java:229-243,286-287);
  reader is floor/higher over the sorted set (SplittingBAMIndex.java:78-83).
- Bai: the standard BAM index; exposes the linear index (the reference's
  htsjdk/samtools/LinearBAMIndex.java shim) and interval→chunk-span queries
  (the BAMFileReader.getFileSpan path used by filterByInterval,
  BAMInputFormat.java:532-634).
- Tabix: `.tbi` over BGZF text (VCF); interval→span queries used to filter
  VCF splits (VCFInputFormat.java:387-471).
- BgzfBlockIndex: `.bgzfi` — 48-bit big-endian offsets of every Nth gzip
  block (util/BGZFBlockIndexer.java:109-127, util/BGZFBlockIndex.java:73-78).
"""

from __future__ import annotations

import bisect
import struct
from dataclasses import dataclass, field
from typing import BinaryIO, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from . import bgzf

SPLITTING_BAI_EXT = ".splitting-bai"
DEFAULT_GRANULARITY = 4096  # SplittingBAMIndexer.java:70
BAI_MAGIC = b"BAI\x01"
TBI_MAGIC = b"TBI\x01"
MAX_BIN = 37450  # pseudo-bin holding file-level metadata
BGZFI_EXT = ".bgzfi"


# ---------------------------------------------------------------------------
# .splitting-bai
# ---------------------------------------------------------------------------


class SplittingBai:
    """Reader for the `.splitting-bai` format (sorted virtual offsets)."""

    def __init__(self, voffsets: Sequence[int]):
        if len(voffsets) < 1:
            raise IOError(
                "Invalid splitting BAM index: should contain at least the file size"
            )
        prev = -1
        for v in voffsets:
            if v < prev:
                raise IOError(
                    f"Invalid splitting BAM index; offsets not in order: "
                    f"{prev:#x} > {v:#x}"
                )
            prev = v
        self.voffsets: List[int] = list(voffsets)

    @staticmethod
    def load(source: Union[str, bytes, BinaryIO]) -> "SplittingBai":
        if isinstance(source, str):
            with open(source, "rb") as f:
                raw = f.read()
        elif isinstance(source, bytes):
            raw = source
        else:
            raw = source.read()
        if len(raw) % 8 != 0:
            raise IOError("Invalid splitting BAM index: truncated")
        n = len(raw) // 8
        return SplittingBai(list(struct.unpack(f">{n}Q", raw)))

    def save(self, stream: BinaryIO) -> None:
        stream.write(struct.pack(f">{len(self.voffsets)}Q", *self.voffsets))

    def prev_alignment(self, file_pos: int) -> Optional[int]:
        """floor(filePos << 16) (SplittingBAMIndex.java:78-80)."""
        target = file_pos << 16
        i = bisect.bisect_right(self.voffsets, target)
        return self.voffsets[i - 1] if i > 0 else None

    def next_alignment(self, file_pos: int) -> Optional[int]:
        """higher(filePos << 16) (SplittingBAMIndex.java:81-83)."""
        target = file_pos << 16
        i = bisect.bisect_right(self.voffsets, target)
        return self.voffsets[i] if i < len(self.voffsets) else None

    def bam_size(self) -> int:
        return self.voffsets[-1] >> 16

    def size(self) -> int:
        return len(self.voffsets)


class SplittingBaiBuilder:
    """Incremental builder (SplittingBAMIndexer.java:186-202 semantics:
    record the offset of alignment 0 and of every alignment whose
    ``(count+1) % granularity == 0``; finish with ``fileSize << 16``)."""

    def __init__(self, granularity: int = DEFAULT_GRANULARITY):
        if granularity < 1:
            raise ValueError("granularity must be >= 1")
        self.granularity = granularity
        self.count = 0
        self.voffsets: List[int] = []

    def process_alignment(self, virtual_offset: int) -> None:
        if self.count == 0 or (self.count + 1) % self.granularity == 0:
            self.voffsets.append(virtual_offset)
        self.count += 1

    def finish(self, input_size: int) -> SplittingBai:
        self.voffsets.append(input_size << 16)
        return SplittingBai(self.voffsets)


def build_splitting_bai(
    bam_path_or_bytes: Union[str, bytes],
    granularity: int = DEFAULT_GRANULARITY,
) -> SplittingBai:
    """Offline construction from a raw BAM (SplittingBAMIndexer.index,
    :248-290: skip the header blocks, then walk records tracking virtual
    offsets)."""
    from . import bam as bam_mod

    if isinstance(bam_path_or_bytes, str):
        with open(bam_path_or_bytes, "rb") as f:
            raw = f.read()
    else:
        raw = bam_path_or_bytes
    reader = bgzf.BgzfReader(raw)
    bam_mod.read_header_stream(reader)
    builder = SplittingBaiBuilder(granularity)
    while not reader.at_eof:
        voffset = reader.tell_voffset()
        size_bytes = reader.read(4)
        if len(size_bytes) < 4:
            break
        (block_size,) = struct.unpack("<I", size_bytes)
        reader.read_fully(block_size)
        builder.process_alignment(voffset)
    return builder.finish(len(raw))


def merge_splitting_bais(
    indices: Sequence[SplittingBai],
    part_lengths: Sequence[int],
    header_length: int,
    total_length: int,
    out: BinaryIO,
) -> None:
    """Merge per-part indices by shifting virtual offsets by the accumulated
    byte length of preceding parts (util/SAMFileMerger.java:104-148)."""
    shift = header_length
    merged: List[int] = []
    for idx, plen in zip(indices, part_lengths):
        for v in idx.voffsets[:-1]:  # drop each part's terminator
            merged.append(((v >> 16) + shift) << 16 | (v & 0xFFFF))
        shift += plen
    merged.append(total_length << 16)
    SplittingBai(merged).save(out)


# ---------------------------------------------------------------------------
# Binning scheme shared by BAI and tabix
# ---------------------------------------------------------------------------


def reg2bins(beg: int, end: int) -> List[int]:
    """All bins overlapping [beg, end), 0-based half-open (SAM spec §5.3)."""
    if beg >= end:
        return [0]
    end -= 1
    bins = [0]
    for shift, offset in ((26, 1), (23, 9), (20, 73), (17, 585), (14, 4681)):
        bins.extend(range(offset + (beg >> shift), offset + (end >> shift) + 1))
    return bins


@dataclass
class Chunk:
    beg: int  # virtual offsets
    end: int


@dataclass
class RefIndex:
    bins: Dict[int, List[Chunk]] = field(default_factory=dict)
    linear: List[int] = field(default_factory=list)  # 16KiB-window voffsets


def _read_ref_index(buf: bytes, p: int) -> Tuple[RefIndex, int]:
    (n_bin,) = struct.unpack_from("<i", buf, p)
    p += 4
    ref = RefIndex()
    for _ in range(n_bin):
        bin_, n_chunk = struct.unpack_from("<Ii", buf, p)
        p += 8
        chunks = []
        for _ in range(n_chunk):
            beg, end = struct.unpack_from("<QQ", buf, p)
            p += 16
            chunks.append(Chunk(beg, end))
        ref.bins[bin_] = chunks
    (n_intv,) = struct.unpack_from("<i", buf, p)
    p += 4
    ref.linear = list(struct.unpack_from(f"<{n_intv}Q", buf, p))
    p += 8 * n_intv
    return ref, p


def _query_ref(ref: RefIndex, beg: int, end: int) -> List[Chunk]:
    """Interval → merged chunk list, clipped by the linear index."""
    min_off = 0
    win = beg >> 14
    if ref.linear:
        min_off = ref.linear[min(win, len(ref.linear) - 1)] if win < len(
            ref.linear
        ) else ref.linear[-1]
    chunks: List[Chunk] = []
    for b in reg2bins(beg, end):
        if b == MAX_BIN:
            continue
        for c in ref.bins.get(b, ()):
            if c.end > min_off:
                chunks.append(Chunk(max(c.beg, min_off), c.end))
    chunks.sort(key=lambda c: (c.beg, c.end))
    merged: List[Chunk] = []
    for c in chunks:
        if merged and c.beg <= merged[-1].end:
            merged[-1].end = max(merged[-1].end, c.end)
        else:
            merged.append(Chunk(c.beg, c.end))
    return merged


class Bai:
    """Standard `.bai` reader with linear-index access and span queries."""

    def __init__(self, refs: List[RefIndex], n_no_coor: Optional[int] = None):
        self.refs = refs
        self.n_no_coor = n_no_coor

    @staticmethod
    def load(source: Union[str, bytes]) -> "Bai":
        raw = (
            open(source, "rb").read() if isinstance(source, str) else source
        )
        if raw[:4] != BAI_MAGIC:
            raise IOError("missing BAI magic")
        (n_ref,) = struct.unpack_from("<i", raw, 4)
        p = 8
        refs = []
        for _ in range(n_ref):
            ref, p = _read_ref_index(raw, p)
            refs.append(ref)
        n_no_coor = None
        if p + 8 <= len(raw):
            (n_no_coor,) = struct.unpack_from("<Q", raw, p)
        return Bai(refs, n_no_coor)

    def linear_index(self, refid: int) -> List[int]:
        """The reference's LinearBAMIndex shim equivalent."""
        return self.refs[refid].linear

    def query(self, refid: int, beg: int, end: int) -> List[Chunk]:
        """Chunk spans possibly containing records overlapping [beg, end)
        (0-based).  The getFileSpan path of filterByInterval."""
        if refid < 0 or refid >= len(self.refs):
            return []
        return _query_ref(self.refs[refid], beg, end)

    def first_offset(self) -> Optional[int]:
        """Smallest chunk start across the whole index."""
        best: Optional[int] = None
        for ref in self.refs:
            for b, chunks in ref.bins.items():
                if b == MAX_BIN:
                    continue
                for c in chunks:
                    if best is None or c.beg < best:
                        best = c.beg
        return best

    def save(self, stream: BinaryIO) -> None:
        stream.write(BAI_MAGIC)
        stream.write(struct.pack("<i", len(self.refs)))
        for ref in self.refs:
            stream.write(struct.pack("<i", len(ref.bins)))
            for bin_ in sorted(ref.bins):
                chunks = ref.bins[bin_]
                stream.write(struct.pack("<Ii", bin_, len(chunks)))
                for c in chunks:
                    stream.write(struct.pack("<QQ", c.beg, c.end))
            stream.write(struct.pack("<i", len(ref.linear)))
            for v in ref.linear:
                stream.write(struct.pack("<Q", v))
        stream.write(struct.pack("<Q", self.n_no_coor or 0))

    def unmapped_span_start(self) -> Optional[int]:
        """Upper bound voffset of all mapped chunks — where the unmapped tail
        begins (BAMInputFormat.java:576-584 semantics)."""
        best: Optional[int] = None
        for ref in self.refs:
            for b, chunks in ref.bins.items():
                if b == MAX_BIN:
                    continue
                for c in chunks:
                    if best is None or c.end > best:
                        best = c.end
        return best


class BaiBuilder:
    """Construct a `.bai` from (record, virtual offset) pairs.

    The reference relies on htsjdk to build `.bai`s; this builder exists so
    the TPU framework is self-contained (and so the query path is testable
    without external fixtures).  Linear index granularity is the standard
    16KiB window; chunks within a bin are merged when adjacent in file order.
    """

    def __init__(self, n_refs: int):
        self.refs = [RefIndex() for _ in range(n_refs)]
        self.n_no_coor = 0

    def add(self, refid: int, pos: int, end_pos: int, bin_: int,
            vstart: int, vend: int) -> None:
        """``end_pos`` is the 0-based exclusive alignment end; ``vstart`` /
        ``vend`` bracket the record's bytes in the BGZF stream."""
        if refid < 0 or pos < 0:
            self.n_no_coor += 1
            return
        ref = self.refs[refid]
        chunks = ref.bins.setdefault(bin_, [])
        if chunks and chunks[-1].end == vstart:
            chunks[-1].end = vend
        else:
            chunks.append(Chunk(vstart, vend))
        win_lo = pos >> 14
        win_hi = max(pos, end_pos - 1) >> 14
        if len(ref.linear) <= win_hi:
            ref.linear.extend([0] * (win_hi + 1 - len(ref.linear)))
        for w in range(win_lo, win_hi + 1):
            if ref.linear[w] == 0 or vstart < ref.linear[w]:
                ref.linear[w] = vstart

    def build(self) -> "Bai":
        return Bai(self.refs, self.n_no_coor)

    def save(self, stream: BinaryIO) -> None:
        self.build().save(stream)


def build_bai(bam_path_or_bytes: Union[str, bytes]) -> "Bai":
    """Build a `.bai` by walking a coordinate-sorted BAM."""
    from . import bam as bam_mod

    raw = (
        open(bam_path_or_bytes, "rb").read()
        if isinstance(bam_path_or_bytes, str)
        else bam_path_or_bytes
    )
    reader = bgzf.BgzfReader(raw)
    hdr = bam_mod.read_header_stream(reader)
    builder = BaiBuilder(hdr.n_refs)
    while not reader.at_eof:
        vstart = reader.tell_voffset()
        size_bytes = reader.read(4)
        if len(size_bytes) < 4:
            break
        (block_size,) = struct.unpack("<I", size_bytes)
        body = reader.read_fully(block_size)
        vend = reader.tell_voffset()
        rec, _ = bam_mod.decode_record(size_bytes + body, 0)
        span = rec.reference_length()
        builder.add(
            rec.refid, rec.pos, rec.pos + max(1, span), rec.bin, vstart, vend
        )
    return builder.build()


class Tabix:
    """`.tbi` reader (BGZF-compressed) with interval span queries."""

    def __init__(
        self,
        refs: List[RefIndex],
        names: List[str],
        fmt: int,
        col_seq: int,
        col_beg: int,
        col_end: int,
        meta_char: str,
        skip: int,
    ):
        self.refs = refs
        self.names = names
        self.fmt = fmt
        self.col_seq = col_seq
        self.col_beg = col_beg
        self.col_end = col_end
        self.meta_char = meta_char
        self.skip = skip
        self._name_to_id = {n: i for i, n in enumerate(names)}

    @staticmethod
    def load(source: Union[str, bytes]) -> "Tabix":
        raw = (
            open(source, "rb").read() if isinstance(source, str) else source
        )
        buf = bgzf.decompress_all(raw) if bgzf.is_bgzf(raw) else raw
        if buf[:4] != TBI_MAGIC:
            raise IOError("missing TBI magic")
        n_ref, fmt, col_seq, col_beg, col_end, meta, skip, l_nm = (
            struct.unpack_from("<8i", buf, 4)
        )
        p = 36
        names = buf[p : p + l_nm].rstrip(b"\x00").split(b"\x00")
        names = [n.decode() for n in names]
        p += l_nm
        refs = []
        for _ in range(n_ref):
            ref, p = _read_ref_index(buf, p)
            refs.append(ref)
        return Tabix(refs, names, fmt, col_seq, col_beg, col_end, chr(meta), skip)

    def ref_id(self, name: str) -> int:
        return self._name_to_id.get(name, -1)

    def query(self, contig: str, beg: int, end: int) -> List[Chunk]:
        rid = self.ref_id(contig)
        if rid < 0:
            return []
        return _query_ref(self.refs[rid], beg, end)


# ---------------------------------------------------------------------------
# .bgzfi
# ---------------------------------------------------------------------------


class BgzfBlockIndex:
    """`.bgzfi`: 48-bit big-endian offsets of every Nth gzip block, plus the
    file size as final entry (util/BGZFBlockIndexer.java:109-127)."""

    def __init__(self, offsets: Sequence[int]):
        self.offsets = sorted(offsets)

    @staticmethod
    def load(source: Union[str, bytes]) -> "BgzfBlockIndex":
        raw = (
            open(source, "rb").read() if isinstance(source, str) else source
        )
        if len(raw) % 6 != 0:
            raise IOError("invalid .bgzfi: not a multiple of 6 bytes")
        offs = [
            int.from_bytes(raw[i : i + 6], "big") for i in range(0, len(raw), 6)
        ]
        return BgzfBlockIndex(offs)

    def save(self, stream: BinaryIO) -> None:
        for o in self.offsets:
            stream.write(o.to_bytes(6, "big"))

    @staticmethod
    def build(
        bgzf_bytes: bytes, granularity: int = 1024
    ) -> "BgzfBlockIndex":
        """Index every granularity-th block + the file size
        (util/BGZFBlockIndexer.java:37-41 default g=1024)."""
        offs = []
        for i, b in enumerate(bgzf.scan_blocks(bgzf_bytes)):
            if i % granularity == 0:
                offs.append(b.coffset)
        offs.append(len(bgzf_bytes))
        return BgzfBlockIndex(offs)

    def prev_block(self, pos: int) -> Optional[int]:
        i = bisect.bisect_right(self.offsets, pos)
        return self.offsets[i - 1] if i > 0 else None

    def next_block(self, pos: int) -> Optional[int]:
        i = bisect.bisect_right(self.offsets, pos)
        return self.offsets[i] if i < len(self.offsets) else None

    def size(self) -> int:
        return len(self.offsets)
