"""Pure-Python/NumPy golden-oracle implementations of the on-disk formats.

This is stage 1 of the build plan (SURVEY.md §7): slow, obviously-correct
reference implementations of BGZF framing, BAM record layout, index formats,
and key functions.  The C++ host library and the Pallas device kernels are
validated against these oracles; the oracles themselves are validated against
htsjdk/samtools-written fixtures.
"""
