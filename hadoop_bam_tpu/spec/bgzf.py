"""BGZF framing: the blocked-gzip container under BAM/BCF/tabixed text.

Golden-oracle implementation (pure Python + zlib) of:

- block header parse/scan (reference BaseSplitGuesser.java:31-108 semantics:
  gzip magic ``1f 8b 08 04``, XLEN subfield walk to the ``BC`` subfield
  carrying BSIZE = total block size - 1),
- block-at-a-time inflate with CRC32 verification (the behavior htsjdk's
  ``BlockCompressedInputStream`` provides below reference L2),
- virtual offsets ``coffset << 16 | uoffset`` (FileVirtualSplit.java:73-78),
- block-at-a-time deflate, including the *omitted terminator* mode used for
  concatenable headerless parts (BGZFCompressionOutputStream.java:9-15,43-46),
- the 28-byte BGZF EOF terminator (appended at merge time,
  util/SAMFileMerger.java:96-102).

The batched/hot equivalents live in native/ (C++) and ops/ (device kernels);
they are tested against this module.
"""

from __future__ import annotations

import io
import struct
import zlib
from dataclasses import dataclass
from typing import BinaryIO, Iterator, List, Optional, Tuple, Union

from .. import faults
from ..utils.tracing import METRICS

# Gzip member header with FEXTRA, as 4 leading magic bytes.
MAGIC = b"\x1f\x8b\x08\x04"
# The BC extra subfield: SI1='B', SI2='C', SLEN=2.
_BC_ID = b"BC"
# Limit input payload per block so worst-case deflate still fits 64KiB.
MAX_PAYLOAD = 0xFF00  # 65280, the conventional BGZF input cap
MAX_BLOCK_SIZE = 0x10000  # 65536: BSIZE is a u16 + 1

# The canonical 28-byte EOF terminator: an empty payload block with
# MTIME=0, XFL=0, OS=0xff, BSIZE=27, empty fixed-Huffman deflate stream
# (03 00), CRC32=0, ISIZE=0.  (Same bytes as the reference's
# bgzf-terminator.bin resource, constructed here from the spec.)
TERMINATOR = (
    b"\x1f\x8b\x08\x04\x00\x00\x00\x00\x00\xff\x06\x00\x42\x43\x02\x00"
    b"\x1b\x00\x03\x00\x00\x00\x00\x00\x00\x00\x00\x00"
)


class BgzfError(IOError):
    pass


def has_eof_terminator(data: bytes) -> bool:
    """Does the stream end with the canonical 28-byte BGZF EOF marker?

    htsjdk's ``BlockCompressedInputStream.checkTermination`` equivalent:
    a missing marker is the signature of a truncated file (a writer that
    died before close), worth flagging *before* a job walks gigabytes to
    the torn tail."""
    return len(data) >= len(TERMINATOR) and data[-len(TERMINATOR):] == TERMINATOR


@dataclass(frozen=True)
class BlockInfo:
    """One BGZF block: compressed extent and inflated size."""

    coffset: int  # compressed file offset of block start
    csize: int  # total compressed block size (header+deflate+footer)
    usize: int  # uncompressed payload size (ISIZE)


def make_voffset(coffset: int, uoffset: int) -> int:
    return (coffset << 16) | uoffset


def split_voffset(voffset: int) -> Tuple[int, int]:
    return voffset >> 16, voffset & 0xFFFF


def parse_block_header(buf: bytes, pos: int = 0) -> Optional[Tuple[int, int]]:
    """Parse a BGZF block header at ``pos`` in ``buf``.

    Returns ``(bsize, xlen)`` where bsize is the total block size, or None if
    this is not a valid BGZF block header.  Mirrors the subfield walk of
    reference BaseSplitGuesser.guessNextBGZFPos (BaseSplitGuesser.java:44-98):
    the BC subfield may appear anywhere in the extra field.
    """
    if pos + 12 > len(buf) or buf[pos : pos + 4] != MAGIC:
        return None
    xlen = struct.unpack_from("<H", buf, pos + 10)[0]
    if pos + 12 + xlen > len(buf):
        return None
    sub = pos + 12
    end = pos + 12 + xlen
    while sub + 4 <= end:
        si = buf[sub : sub + 2]
        slen = struct.unpack_from("<H", buf, sub + 2)[0]
        if si == _BC_ID and slen == 2:
            if sub + 6 > end:
                return None
            bsize = struct.unpack_from("<H", buf, sub + 4)[0] + 1
            if bsize < 12 + xlen + 8 or bsize > MAX_BLOCK_SIZE:
                return None
            # The remaining subfields must walk to *exactly* the end of the
            # extra field, else the guess is cancelled
            # (BaseSplitGuesser.java:80-90).
            walk = sub + 6
            while walk < end:
                if walk + 4 > end:
                    return None
                walk += 4 + struct.unpack_from("<H", buf, walk + 2)[0]
            if walk != end:
                return None
            return bsize, xlen
        sub += 4 + slen
    return None


def read_block_at(buf, pos: int) -> Tuple[int, int]:
    """(csize, usize) of the BGZF block at ``pos``, ISIZE-validated — the one
    shared header probe used by every chain walker."""
    hdr = parse_block_header(buf, pos)
    if hdr is None:
        raise BgzfError(f"bad BGZF block at {pos}")
    if pos + hdr[0] > len(buf):
        raise BgzfError(f"truncated BGZF block at offset {pos}")
    usize = struct.unpack_from("<I", buf, pos + hdr[0] - 4)[0]
    if usize > MAX_BLOCK_SIZE:
        raise BgzfError(f"ISIZE {usize} beyond BGZF bound at {pos}")
    return hdr[0], usize


def find_next_block(buf: bytes, start: int = 0) -> Optional[Tuple[int, int]]:
    """Scan ``buf`` from ``start`` for the next plausible BGZF block header.

    Returns ``(pos, usize)`` like the reference's guesser
    (BaseSplitGuesser.java:31-108): usize is the ISIZE read from the block
    footer located via BSIZE.  Candidates whose footer lies beyond the buffer
    are rejected (caller re-buffers).
    """
    pos = start
    n = len(buf)
    while True:
        pos = buf.find(MAGIC[:2], pos)
        if pos < 0 or pos + 4 > n:
            return None
        hdr = parse_block_header(buf, pos)
        if hdr is not None:
            bsize, _ = hdr
            if pos + bsize <= n:
                usize = struct.unpack_from("<I", buf, pos + bsize - 4)[0]
                if usize <= MAX_BLOCK_SIZE:
                    return pos, usize
        pos += 1


def inflate_block(buf: bytes, pos: int = 0, check_crc: bool = True) -> Tuple[bytes, int]:
    """Inflate one BGZF block at ``pos``; returns (payload, csize).

    CRC32 is verified by default, mirroring the guessers'
    ``setCheckCrcs(true)`` (BAMSplitGuesser.java:143).
    """
    hdr = parse_block_header(buf, pos)
    if hdr is None:
        raise BgzfError(f"not a BGZF block at offset {pos}")
    bsize, xlen = hdr
    if pos + bsize > len(buf):
        raise BgzfError("truncated BGZF block")
    cdata_off = pos + 12 + xlen
    cdata_len = bsize - (12 + xlen) - 8
    try:
        payload = zlib.decompress(buf[cdata_off : cdata_off + cdata_len], wbits=-15)
    except zlib.error as e:
        raise BgzfError(f"corrupt deflate stream at offset {pos}: {e}") from e
    if faults.ACTIVE is not None:
        # Detected-corruption seam: the flip happens BEFORE the CRC gate,
        # so the framing check — not luck — is what catches it.
        payload = faults.ACTIVE.corrupt_payload(payload)
    crc, isize = struct.unpack_from("<II", buf, pos + bsize - 8)
    if len(payload) != isize:
        raise BgzfError(f"ISIZE mismatch at {pos}: {len(payload)} != {isize}")
    if check_crc and (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise BgzfError(f"CRC mismatch in BGZF block at {pos}")
    return payload, bsize


def compress_block(payload: bytes, level: int = 6) -> bytes:
    """Deflate one payload (≤ MAX_PAYLOAD bytes) into a full BGZF block."""
    if len(payload) > MAX_PAYLOAD:
        raise BgzfError(f"payload too large for one BGZF block: {len(payload)}")
    co = zlib.compressobj(level, zlib.DEFLATED, -15)
    cdata = co.compress(payload) + co.flush(zlib.Z_FINISH)
    bsize = len(cdata) + 12 + 6 + 8
    if bsize > MAX_BLOCK_SIZE:
        # Incompressible data at low levels can overflow; store uncompressed.
        co = zlib.compressobj(0, zlib.DEFLATED, -15)
        cdata = co.compress(payload) + co.flush(zlib.Z_FINISH)
        bsize = len(cdata) + 12 + 6 + 8
        if bsize > MAX_BLOCK_SIZE:
            raise BgzfError("cannot fit payload into one BGZF block")
    header = MAGIC + struct.pack(
        "<IBBHBBHH",
        0,  # MTIME
        0,  # XFL
        0xFF,  # OS = unknown
        6,  # XLEN
        0x42,  # 'B'
        0x43,  # 'C'
        2,  # SLEN
        bsize - 1,  # BSIZE
    )
    footer = struct.pack("<II", zlib.crc32(payload) & 0xFFFFFFFF, len(payload))
    return header + cdata + footer


def scan_blocks(data: bytes) -> List[BlockInfo]:
    """Walk blocks back-to-back from offset 0 (no guessing)."""
    out: List[BlockInfo] = []
    pos = 0
    while pos < len(data):
        hdr = parse_block_header(data, pos)
        if hdr is None:
            raise BgzfError(f"bad BGZF chain at offset {pos}")
        bsize, _ = hdr
        if pos + bsize > len(data):
            raise BgzfError(f"truncated BGZF block at offset {pos}")
        usize = struct.unpack_from("<I", data, pos + bsize - 4)[0]
        if usize > MAX_BLOCK_SIZE:
            raise BgzfError(f"ISIZE {usize} beyond BGZF bound at offset {pos}")
        out.append(BlockInfo(pos, bsize, usize))
        pos += bsize
    return out


def is_bgzf(data: bytes) -> bool:
    """First-block validity sniff (htsjdk BlockCompressedInputStream
    .isValidFile equivalent, used by BGZFEnhancedGzipCodec.java:44-73 and
    VCFInputFormat.java:198-224)."""
    return parse_block_header(data, 0) is not None


def decompress_all(data: bytes) -> bytes:
    return b"".join(
        inflate_block(data, b.coffset)[0] for b in scan_blocks(data)
    )


class BgzfReader:
    """Random-access reader addressed by virtual offsets.

    The oracle equivalent of htsjdk's BlockCompressedInputStream as used by
    the record readers (e.g. BAMRecordReader.java:179-183 iterating
    ``[vStart, vEnd)``).  One-block cache; sequential reads walk the chain.

    ``check_eof`` (default: on for whole-file ``str`` sources, off for
    byte windows, which legitimately end mid-stream) probes for the
    28-byte BGZF EOF terminator at open — htsjdk's truncated-file
    warning — setting :attr:`truncated` and counting ``bgzf.missing_eof``
    when it is absent.  ``errors="salvage"`` makes a torn tail (a final
    member that fails to parse/inflate) a clean EOF at the last whole
    member (``salvage.torn_tail`` counter) instead of the strict-mode
    raise.
    """

    def __init__(
        self,
        source: Union[str, bytes, BinaryIO],
        errors: str = "strict",
        check_eof: Optional[bool] = None,
    ):
        if isinstance(source, (str,)):
            with open(source, "rb") as f:
                self._data = f.read()
            if check_eof is None:
                check_eof = True
        elif isinstance(source, bytes):
            self._data = source
        else:
            self._data = source.read()
        if errors not in ("strict", "salvage"):
            raise ValueError(f"errors must be strict|salvage, got {errors!r}")
        self._errors = errors
        #: None = not probed (windowed source); else the missing-EOF flag.
        self.truncated: Optional[bool] = None
        if check_eof:
            self.truncated = not has_eof_terminator(self._data)
            if self.truncated:
                METRICS.count("bgzf.missing_eof", 1)
        self._coffset = 0
        self._uoffset = 0
        self._block: Optional[bytes] = None
        self._block_csize = 0

    def _load(self) -> bool:
        if self._block is not None:
            return True
        if self._coffset >= len(self._data):
            return False
        try:
            payload, csize = inflate_block(self._data, self._coffset)
        except BgzfError:
            if self._errors != "salvage":
                raise
            # Torn tail: stop cleanly at the last whole member.
            METRICS.count("salvage.torn_tail", 1)
            self._coffset = len(self._data)
            return False
        self._block = payload
        self._block_csize = csize
        return True

    def seek_voffset(self, voffset: int) -> None:
        co, uo = split_voffset(voffset)
        if co != self._coffset:
            self._coffset = co
            self._block = None
        self._uoffset = uo

    def tell_voffset(self) -> int:
        # Normalized: at end-of-block, report the start of the next block,
        # as htsjdk does, so voffset comparisons are monotone.
        if self._block is not None and self._uoffset >= len(self._block):
            return make_voffset(self._coffset + self._block_csize, 0)
        return make_voffset(self._coffset, self._uoffset)

    def read(self, n: int) -> bytes:
        out = io.BytesIO()
        need = n
        while need > 0:
            if not self._load():
                break
            block = self._block
            assert block is not None
            avail = len(block) - self._uoffset
            if avail <= 0:
                self._coffset += self._block_csize
                self._uoffset = 0
                self._block = None
                continue
            take = min(avail, need)
            out.write(block[self._uoffset : self._uoffset + take])
            self._uoffset += take
            need -= take
        return out.getvalue()

    def read_fully(self, n: int) -> bytes:
        b = self.read(n)
        if len(b) != n:
            raise BgzfError(f"EOF: wanted {n} bytes, got {len(b)}")
        return b

    @property
    def at_eof(self) -> bool:
        if self._coffset >= len(self._data):
            return True
        if self._block is not None and self._uoffset >= len(self._block):
            return self._coffset + self._block_csize >= len(self._data)
        return False


class BgzfWriter:
    """Block-at-a-time BGZF writer.

    ``append_terminator=False`` reproduces the reference's concatenable
    headerless-part behavior: BGZFCompressionOutputStream deliberately omits
    the empty-block terminator on close so part files can be concatenated and
    terminated once at merge time (BGZFCompressionOutputStream.java:9-15,43-46,
    util/SAMFileMerger.java:96-102).
    """

    def __init__(
        self,
        stream: BinaryIO,
        level: int = 6,
        append_terminator: bool = True,
    ):
        self._stream = stream
        self._level = level
        self._append_terminator = append_terminator
        self._buf = bytearray()
        self._coffset = 0  # compressed bytes written so far
        self._closed = False

    def write(self, data: bytes) -> None:
        self._buf.extend(data)
        while len(self._buf) >= MAX_PAYLOAD:
            self._flush_block(MAX_PAYLOAD)

    def _flush_block(self, n: int) -> None:
        payload = bytes(self._buf[:n])
        del self._buf[:n]
        block = compress_block(payload, self._level)
        self._stream.write(block)
        self._coffset += len(block)

    def flush(self) -> None:
        while self._buf:
            self._flush_block(min(len(self._buf), MAX_PAYLOAD))

    def tell_voffset(self) -> int:
        """Virtual offset where the next byte written will land."""
        return make_voffset(self._coffset, len(self._buf))

    def close(self) -> None:
        if self._closed:
            return
        self.flush()
        if self._append_terminator:
            self._stream.write(TERMINATOR)
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
