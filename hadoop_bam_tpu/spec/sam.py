"""SAM text format: line ↔ binary record conversion, incl. the tag codec.

The role htsjdk's ``SAMTextWriter``/text parsing plays under reference L4
(SAMRecordReader.java / SAMRecordWriter.java).  SAM lines convert to the
*binary* record representation (spec/bam.BamRecord) on read, so text inputs
flow through the same SoA decode → key → sort pipeline as BAM; writers
convert back, preserving optional tags.

Tag wire format (SAM spec §4.2.4 / BAM §4.2): two-char tag, type byte
(A c C s S i I f Z H B), value; ``B`` arrays carry an element type + count.
SAM text types map to the smallest-loss BAM types the way htsjdk does
(integers always as ``i`` on text, narrowed on binary encode only by value).
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

from . import bam


class SamError(IOError):
    pass


def _encode_tag(tag: str, typ: str, value: str) -> bytes:
    out = tag.encode()
    if typ == "A":
        return out + b"A" + value.encode()[:1]
    if typ == "i":
        v = int(value)
        # htsjdk narrows by value range on binary encode.
        for code, fmt, lo, hi in (
            (b"c", "<b", -128, 127),
            (b"C", "<B", 0, 255),
            (b"s", "<h", -32768, 32767),
            (b"S", "<H", 0, 65535),
            (b"i", "<i", -(1 << 31), (1 << 31) - 1),
            (b"I", "<I", 0, (1 << 32) - 1),
        ):
            if lo <= v <= hi:
                return out + code + struct.pack(fmt, v)
        raise SamError(f"integer tag out of range: {tag}={value}")
    if typ == "f":
        return out + b"f" + struct.pack("<f", float(value))
    if typ in ("Z", "H"):
        return out + typ.encode() + value.encode() + b"\x00"
    if typ == "B":
        parts = value.split(",")
        elem = parts[0]
        nums = parts[1:]
        fmt = {"c": "<b", "C": "<B", "s": "<h", "S": "<H", "i": "<i",
               "I": "<I", "f": "<f"}[elem]
        conv = float if elem == "f" else int
        body = b"".join(struct.pack(fmt, conv(x)) for x in nums)
        return out + b"B" + elem.encode() + struct.pack("<I", len(nums)) + body
    raise SamError(f"unknown tag type {typ}")


def decode_tags(raw: bytes) -> List[Tuple[str, str, str]]:
    """BAM tag block → [(tag, sam_type, sam_value)] (binary ints → 'i')."""
    out: List[Tuple[str, str, str]] = []
    p = 0
    n = len(raw)
    while p + 3 <= n:
        tag = raw[p : p + 2].decode()
        typ = chr(raw[p + 2])
        p += 3
        if typ == "A":
            out.append((tag, "A", chr(raw[p])))
            p += 1
        elif typ in "cCsSiI":
            fmt = {"c": "<b", "C": "<B", "s": "<h", "S": "<H",
                   "i": "<i", "I": "<I"}[typ]
            size = struct.calcsize(fmt)
            (v,) = struct.unpack_from(fmt, raw, p)
            out.append((tag, "i", str(v)))
            p += size
        elif typ == "f":
            (v,) = struct.unpack_from("<f", raw, p)
            out.append((tag, "f", f"{v:g}"))
            p += 4
        elif typ in "ZH":
            end = raw.index(b"\x00", p)
            out.append((tag, typ, raw[p:end].decode()))
            p = end + 1
        elif typ == "B":
            elem = chr(raw[p])
            (count,) = struct.unpack_from("<I", raw, p + 1)
            p += 5
            fmt = {"c": "<b", "C": "<B", "s": "<h", "S": "<H", "i": "<i",
                   "I": "<I", "f": "<f"}[elem]
            size = struct.calcsize(fmt)
            vals = [
                struct.unpack_from(fmt, raw, p + i * size)[0]
                for i in range(count)
            ]
            rendered = ",".join(
                f"{v:g}" if elem == "f" else str(v) for v in vals
            )
            out.append((tag, "B", f"{elem},{rendered}" if vals else elem))
            p += count * size
        else:
            raise SamError(f"unknown binary tag type {typ!r}")
    return out


def parse_cigar(text: str) -> List[Tuple[int, str]]:
    if text == "*":
        return []
    out = []
    num = ""
    for ch in text:
        if ch.isdigit():
            num += ch
        elif ch in bam.CIGAR_OPS:
            if not num:
                raise SamError(f"malformed CIGAR {text!r}")
            out.append((int(num), ch))
            num = ""
        else:
            raise SamError(f"bad CIGAR operator {ch!r} in {text!r}")
    if num:
        raise SamError(f"malformed CIGAR {text!r}")
    return out


def sam_line_to_record(line: str, header: bam.BamHeader) -> bam.BamRecord:
    f = line.rstrip("\n").split("\t")
    if len(f) < 11:
        raise SamError(f"SAM line has {len(f)} fields (need >= 11)")
    qname, flag_s, rname, pos_s, mapq_s, cigar_s, rnext, pnext_s, tlen_s, seq, qual = f[:11]
    try:
        flag = int(flag_s)
        pos1 = int(pos_s)
        mapq = int(mapq_s)
        pnext1 = int(pnext_s)
        tlen = int(tlen_s)
    except ValueError as e:
        raise SamError(f"non-integer core field in SAM line: {e}")
    refid = header.ref_index(rname)
    if rnext == "=":
        nrefid = refid
    else:
        nrefid = header.ref_index(rnext)
    tags = b"".join(
        _encode_tag(t[:2], t[3], t[5:]) for t in f[11:] if len(t) >= 5
    )
    return bam.build_record(
        name="" if qname == "*" else qname,
        refid=refid,
        pos=pos1 - 1,
        mapq=mapq,
        flag=flag,
        cigar=parse_cigar(cigar_s),
        seq=seq,
        qual=qual if qual == "*" else bytes(ord(c) - 33 for c in qual),
        next_refid=nrefid,
        next_pos=pnext1 - 1,
        tlen=tlen,
        tags=tags,
    )


def record_to_sam_line(rec: bam.BamRecord, header: bam.BamHeader) -> str:
    qual = rec.qual
    qual_s = (
        "*"
        if not qual or all(q == 0xFF for q in qual)
        else "".join(chr(q + 33) for q in qual)
    )
    rname = header.ref_name(rec.refid)
    if rec.next_refid < 0:
        rnext = "*"
    elif rec.next_refid == rec.refid:
        rnext = "="
    else:
        rnext = header.ref_name(rec.next_refid)
    fields = [
        rec.read_name or "*",
        str(rec.flag),
        rname,
        str(rec.pos + 1),
        str(rec.mapq),
        rec.cigar_string(),
        rnext,
        str(rec.next_pos + 1),
        str(rec.tlen),
        rec.seq,
        qual_s,
    ]
    for tag, typ, val in decode_tags(rec.tags_raw):
        fields.append(f"{tag}:{typ}:{val}")
    return "\t".join(fields)


def read_sam(text_or_bytes) -> Tuple[bam.BamHeader, List[bam.BamRecord]]:
    text = (
        text_or_bytes.decode()
        if isinstance(text_or_bytes, bytes)
        else text_or_bytes
    )
    header_lines: List[str] = []
    body: List[str] = []
    refs: List[Tuple[str, int]] = []
    for line in text.split("\n"):
        if not line:
            continue
        if line.startswith("@"):
            header_lines.append(line)
            if line.startswith("@SQ"):
                name, length = None, None
                for fld in line.split("\t")[1:]:
                    if fld.startswith("SN:"):
                        name = fld[3:]
                    elif fld.startswith("LN:"):
                        length = int(fld[3:])
                if name is not None and length is not None:
                    refs.append((name, length))
        else:
            body.append(line)
    header = bam.BamHeader("\n".join(header_lines), refs)
    return header, [sam_line_to_record(l, header) for l in body]


def write_sam(
    stream, header: bam.BamHeader, records, write_header: bool = True
) -> None:
    if write_header and header.text:
        stream.write((header.text.rstrip("\n") + "\n").encode())
    for rec in records:
        stream.write((record_to_sam_line(rec, header) + "\n").encode())
