"""BCF2 binary format: dictionaries, typed values, record codec.

Oracle implementation of the role htsjdk's ``BCF2Codec``/``BCF2Encoder`` play
under the reference's BCF path (BCFRecordReader.java, BCFSplitGuesser.java).
Layout per the BCF2.2 section of the VCF spec:

- file = BGZF stream; uncompressed payload starts ``BCF\\x02\\x02``, then
  ``l_text`` (u32) + NUL-terminated VCF header text,
- each site: ``l_shared`` (u32), ``l_indiv`` (u32), shared block
  (CHROM i32, POS i32 0-based, rlen i32, QUAL f32 with signaling-NaN
  0x7F800001 for missing, n_allele<<16|n_info u32, n_fmt<<24|n_sample u32,
  ID typed string, alleles, FILTER typed int vector, INFO key/value pairs),
  then the genotype (indiv) block: n_fmt × (typed key, typed vector).

Genotype blocks are kept **unparsed** on decode (``LazyBcfGenotypes``) — the
reference's LazyBCFGenotypesContext stance (LazyBCFGenotypesContext.java:42-149):
sorting/filtering variants never pays genotype-parse cost; text materialises
only when a writer or user asks for it.
"""

from __future__ import annotations

import re
import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..utils.intervals import FormatError as FormatException
from .vcf import VariantContext, VcfHeader

MAGIC = b"BCF\x02\x02"

# type codes (low nibble of the descriptor byte)
T_MISSING = 0
T_INT8 = 1
T_INT16 = 2
T_INT32 = 3
T_FLOAT = 5
T_CHAR = 7

# reserved sentinel values per int width: MISSING, END_OF_VECTOR
INT8_MISSING, INT8_EOV = -128, -127
INT16_MISSING, INT16_EOV = -32768, -32767
INT32_MISSING, INT32_EOV = -2147483648, -2147483647
FLOAT_MISSING_BITS = 0x7F800001
FLOAT_EOV_BITS = 0x7F800002

# usable (non-reserved) int ranges per width
_INT8_MIN, _INT8_MAX = -120, 127
_INT16_MIN, _INT16_MAX = -32760, 32767
_INT32_MIN, _INT32_MAX = -2147483640, 2147483647


class BcfError(IOError):
    pass


# ---------------------------------------------------------------------------
# Dictionaries
# ---------------------------------------------------------------------------


@dataclass
class _Declared:
    """One ##INFO/##FORMAT declaration (Type/Number drive encoding)."""

    id: str
    type: str  # Integer | Float | Flag | Character | String
    number: str  # '1', 'A', 'R', 'G', '.', etc.


class BcfHeader:
    """A VcfHeader plus the BCF string/contig dictionaries.

    Dictionary construction follows the spec: if any header line carries an
    ``IDX=`` attribute those indices are authoritative; otherwise the string
    dictionary is the order of first appearance of FILTER/INFO/FORMAT IDs
    with ``PASS`` implicitly at offset 0, and the contig dictionary is
    ##contig line order.
    """

    def __init__(self, vcf: VcfHeader):
        self.vcf = vcf
        strings: List[str] = []
        str_idx: Dict[str, int] = {}
        explicit: Dict[int, str] = {}
        any_idx = False
        self.info: Dict[str, _Declared] = {}
        self.format: Dict[str, _Declared] = {}

        def add(name: str, idx: Optional[int]) -> None:
            nonlocal any_idx
            if idx is not None:
                any_idx = True
                explicit[idx] = name
            elif name not in str_idx:
                str_idx[name] = len(strings)
                strings.append(name)

        if "PASS" not in str_idx:
            str_idx["PASS"] = 0
            strings.append("PASS")
        for ln in vcf.lines:
            m = re.match(r"##(FILTER|INFO|FORMAT)=<(.*)>", ln)
            if not m:
                continue
            kind, body = m.group(1), m.group(2)
            fid = _attr(body, "ID")
            if fid is None:
                continue
            idx_s = _attr(body, "IDX")
            add(fid, int(idx_s) if idx_s is not None else None)
            decl = _Declared(
                fid, _attr(body, "Type") or "String", _attr(body, "Number") or "."
            )
            if kind == "INFO":
                self.info[fid] = decl
            elif kind == "FORMAT":
                self.format[fid] = decl
        if any_idx:
            size = max(explicit) + 1
            strings = [""] * size
            for i, name in explicit.items():
                strings[i] = name
            if "PASS" not in explicit.values():
                strings[0] = "PASS"
            str_idx = {n: i for i, n in enumerate(strings) if n}
        self.strings = strings
        self._str_idx = str_idx
        self.contigs = list(vcf.contigs)
        self._contig_idx = {c: i for i, c in enumerate(self.contigs)}
        self.n_samples = len(vcf.samples)

    def string_index(self, name: str) -> int:
        try:
            return self._str_idx[name]
        except KeyError:
            raise BcfError(f"ID {name!r} not in BCF dictionary")

    def contig_index(self, name: str) -> int:
        try:
            return self._contig_idx[name]
        except KeyError:
            raise BcfError(f"contig {name!r} not in BCF dictionary")


def _attr(body: str, key: str) -> Optional[str]:
    m = re.search(rf'(?:^|,){key}=("[^"]*"|[^,]*)', body)
    if not m:
        return None
    v = m.group(1)
    return v[1:-1] if v.startswith('"') else v


# ---------------------------------------------------------------------------
# Typed values
# ---------------------------------------------------------------------------


def read_typed_descriptor(buf, p: int) -> Tuple[int, int, int]:
    """(type, length, new_p); resolves the length==15 overflow form."""
    b = buf[p]
    p += 1
    t, ln = b & 0xF, b >> 4
    if ln == 15:
        vals, p = read_typed_value(buf, p)
        ln = int(vals[0])
    return t, ln, p


def _read_ints(buf, p: int, t: int, n: int) -> Tuple[List[int], int]:
    if t == T_INT8:
        vals = list(struct.unpack_from(f"<{n}b", buf, p))
        return vals, p + n
    if t == T_INT16:
        vals = list(struct.unpack_from(f"<{n}h", buf, p))
        return vals, p + 2 * n
    if t == T_INT32:
        vals = list(struct.unpack_from(f"<{n}i", buf, p))
        return vals, p + 4 * n
    raise BcfError(f"bad int type {t}")


def read_typed_value(buf, p: int):
    """Decode one typed value → (list-or-str, new_p).

    Ints/floats come back as Python lists (missing → None, EOV trimmed);
    char vectors come back as ``str``.
    """
    t, ln, p = read_typed_descriptor(buf, p)
    if t == T_MISSING:
        return [], p
    if t == T_CHAR:
        s = bytes(buf[p : p + ln]).decode("latin-1")
        return s, p + ln
    if t == T_FLOAT:
        out: List[Optional[float]] = []
        for k in range(ln):
            (bits,) = struct.unpack_from("<I", buf, p + 4 * k)
            if bits == FLOAT_MISSING_BITS:
                out.append(None)
            elif bits == FLOAT_EOV_BITS:
                return out, p + 4 * ln
            else:
                out.append(struct.unpack_from("<f", buf, p + 4 * k)[0])
        return out, p + 4 * ln
    raw, p = _read_ints(buf, p, t, ln)
    missing, eov = {
        T_INT8: (INT8_MISSING, INT8_EOV),
        T_INT16: (INT16_MISSING, INT16_EOV),
        T_INT32: (INT32_MISSING, INT32_EOV),
    }[t]
    out = []
    for v in raw:
        if v == eov:
            break
        out.append(None if v == missing else v)
    return out, p


def _int_type_for(vals: List[int]) -> int:
    lo = min(vals) if vals else 0
    hi = max(vals) if vals else 0
    if _INT8_MIN <= lo and hi <= _INT8_MAX:
        return T_INT8
    if _INT16_MIN <= lo and hi <= _INT16_MAX:
        return T_INT16
    return T_INT32


def write_descriptor(out: bytearray, t: int, ln: int) -> None:
    if ln < 15:
        out.append((ln << 4) | t)
    else:
        out.append((15 << 4) | t)
        write_typed_ints(out, [ln])


def write_typed_ints(
    out: bytearray, vals: List[Optional[int]], pad_to: int = 0
) -> None:
    """Typed int vector; ``None`` → MISSING; padding (for fixed-width sample
    matrices) uses END_OF_VECTOR."""
    concrete = [v for v in vals if v is not None]
    t = _int_type_for(concrete)
    n = max(len(vals), pad_to)
    write_descriptor(out, t, n)
    fmt, missing, eov = {
        T_INT8: ("<b", INT8_MISSING, INT8_EOV),
        T_INT16: ("<h", INT16_MISSING, INT16_EOV),
        T_INT32: ("<i", INT32_MISSING, INT32_EOV),
    }[t]
    for v in vals:
        out.extend(struct.pack(fmt, missing if v is None else v))
    for _ in range(n - len(vals)):
        out.extend(struct.pack(fmt, eov))


def write_typed_floats(
    out: bytearray, vals: List[Optional[float]], pad_to: int = 0
) -> None:
    n = max(len(vals), pad_to)
    write_descriptor(out, T_FLOAT, n)
    for v in vals:
        if v is None:
            out.extend(struct.pack("<I", FLOAT_MISSING_BITS))
        else:
            out.extend(struct.pack("<f", v))
    for _ in range(n - len(vals)):
        out.extend(struct.pack("<I", FLOAT_EOV_BITS))


def write_typed_string(out: bytearray, s: str) -> None:
    raw = s.encode("latin-1")
    write_descriptor(out, T_CHAR, len(raw))
    out.extend(raw)


# ---------------------------------------------------------------------------
# Lazy genotypes
# ---------------------------------------------------------------------------


@dataclass
class LazyBcfGenotypes:
    """Undecoded indiv block + the bits needed to materialise VCF text
    (the LazyBCFGenotypesContext equivalent)."""

    header: BcfHeader
    n_fmt: int
    n_sample: int
    raw: bytes

    def to_text(self) -> str:
        """FORMAT + TAB-joined sample columns as VCF text."""
        if self.n_fmt == 0 or self.n_sample == 0:
            return ""
        buf = self.raw
        p = 0
        keys: List[str] = []
        cols: List[List[str]] = []  # per fmt key: one string per sample
        for _ in range(self.n_fmt):
            kidx, p = read_typed_value(buf, p)
            key = self.header.strings[int(kidx[0])]
            keys.append(key)
            t, ln, p = read_typed_descriptor(buf, p)
            per_sample: List[str] = []
            for _s in range(self.n_sample):
                if t == T_CHAR:
                    s = bytes(buf[p : p + ln]).decode("latin-1")
                    p += ln
                    per_sample.append(s.rstrip("\x00") or ".")
                elif t == T_FLOAT:
                    vals = []
                    stop = False
                    for k in range(ln):
                        (bits,) = struct.unpack_from("<I", buf, p + 4 * k)
                        if bits == FLOAT_EOV_BITS:
                            stop = True
                        elif not stop:
                            vals.append(
                                "."
                                if bits == FLOAT_MISSING_BITS
                                else _fmt_float(
                                    struct.unpack_from("<f", buf, p + 4 * k)[0]
                                )
                            )
                    p += 4 * ln
                    per_sample.append(",".join(vals) if vals else ".")
                else:
                    raw_vals, p = _read_ints(buf, p, t, ln)
                    missing, eov = {
                        T_INT8: (INT8_MISSING, INT8_EOV),
                        T_INT16: (INT16_MISSING, INT16_EOV),
                        T_INT32: (INT32_MISSING, INT32_EOV),
                    }[t]
                    if key == "GT":
                        per_sample.append(_gt_text(raw_vals, missing, eov))
                    else:
                        vals = []
                        for v in raw_vals:
                            if v == eov:
                                break
                            vals.append("." if v == missing else str(v))
                        per_sample.append(",".join(vals) if vals else ".")
            cols.append(per_sample)
        sample_cols = [
            ":".join(cols[k][s] for k in range(len(keys)))
            for s in range(self.n_sample)
        ]
        return "\t".join([":".join(keys)] + sample_cols)


def _gt_text(raw_vals: List[int], missing: int, eov: int) -> str:
    parts: List[str] = []
    for i, v in enumerate(raw_vals):
        if v == eov:
            break
        allele = "." if v == missing or (v >> 1) == 0 else str((v >> 1) - 1)
        if i == 0:
            parts.append(allele)
        else:
            parts.append(("|" if v & 1 else "/") + allele)
    return "".join(parts) if parts else "."


def _fmt_float(x: float) -> str:
    return f"{x:g}"


class BcfVariant(VariantContext):
    """VariantContext whose genotype text materialises lazily from the BCF
    indiv block (LazyBCFGenotypesContext.java:42-149 stance)."""

    def __init__(self, *args, lazy: Optional[LazyBcfGenotypes] = None, **kw):
        self._lazy = None
        super().__init__(*args, **kw)
        self._lazy = lazy

    @property  # type: ignore[override]
    def genotypes_raw(self) -> str:  # noqa: D102
        if not self._gt and self._lazy is not None:
            self._gt = self._lazy.to_text()
            self._lazy = None
        return self._gt

    @genotypes_raw.setter
    def genotypes_raw(self, v: str) -> None:
        self._gt = v


# ---------------------------------------------------------------------------
# Record codec
# ---------------------------------------------------------------------------


def decode_record(
    buf, p: int, hdr: BcfHeader
) -> Tuple[BcfVariant, int]:
    """Decode one site starting at ``p`` → (variant, new_p)."""
    l_shared, l_indiv = struct.unpack_from("<II", buf, p)
    body_start = p + 8
    chrom_i, pos0, rlen = struct.unpack_from("<iii", buf, body_start)
    (qual_bits,) = struct.unpack_from("<I", buf, body_start + 12)
    (nai,) = struct.unpack_from("<I", buf, body_start + 16)
    n_allele, n_info = nai >> 16, nai & 0xFFFF
    (nfs,) = struct.unpack_from("<I", buf, body_start + 20)
    n_fmt, n_sample = nfs >> 24, nfs & 0xFFFFFF
    if not (0 <= chrom_i < len(hdr.contigs)):
        raise BcfError(f"CHROM index {chrom_i} out of range")
    q = body_start + 24
    vid, q = read_typed_value(buf, q)
    alleles: List[str] = []
    for _ in range(n_allele):
        a, q = read_typed_value(buf, q)
        alleles.append(a if isinstance(a, str) else "")
    filt_idx, q = read_typed_value(buf, q)
    info_parts: List[str] = []
    for _ in range(n_info):
        kidx, q = read_typed_value(buf, q)
        key = hdr.strings[int(kidx[0])]
        t = buf[q] & 0xF
        val, q = read_typed_value(buf, q)
        decl = hdr.info.get(key)
        if decl is not None and decl.type == "Flag":
            info_parts.append(key)
        else:
            info_parts.append(_info_text(key, t, val))
    if q - body_start != l_shared:
        raise BcfError(
            f"shared block length mismatch: read {q - body_start}, "
            f"declared {l_shared}"
        )
    indiv = bytes(buf[q : q + l_indiv])
    if len(indiv) != l_indiv:
        raise BcfError("truncated indiv block")
    qual = (
        None
        if qual_bits == FLOAT_MISSING_BITS
        else struct.unpack("<f", struct.pack("<I", qual_bits))[0]
    )
    filters = [hdr.strings[int(i)] for i in filt_idx if i is not None]
    ref = alleles[0] if alleles else "N"
    v = BcfVariant(
        chrom=hdr.contigs[chrom_i],
        pos=pos0 + 1,
        id="" if isinstance(vid, list) or vid in (".", "") else vid,
        ref=ref,
        alts=alleles[1:],
        qual=qual,
        filters=filters,
        info=";".join(info_parts) if info_parts else ".",
        genotypes_raw="",
        lazy=LazyBcfGenotypes(hdr, n_fmt, n_sample, indiv),
    )
    return v, q + l_indiv


def _info_text(key: str, t: int, val) -> str:
    if t == T_MISSING or (isinstance(val, list) and not val):
        return key  # Flag
    if isinstance(val, str):
        return f"{key}={val}"
    parts = []
    for x in val:
        if x is None:
            parts.append(".")
        elif isinstance(x, float):
            parts.append(_fmt_float(x))
        else:
            parts.append(str(x))
    return f"{key}={','.join(parts)}"


def encode_record(hdr: BcfHeader, v: VariantContext) -> bytes:
    """Encode one site (the BCF2Encoder role)."""
    shared = bytearray()
    chrom_i = hdr.contig_index(v.chrom)
    alleles = [v.ref] + list(v.alts)
    info_items = _parse_info(v.info)
    gt_text = v.genotypes_raw
    fmt_block, n_fmt = _encode_genotypes(hdr, gt_text)
    n_sample = hdr.n_samples if gt_text else 0
    rlen = v.end - v.pos + 1
    shared.extend(struct.pack("<iii", chrom_i, v.pos - 1, rlen))
    if v.qual is None:
        shared.extend(struct.pack("<I", FLOAT_MISSING_BITS))
    else:
        shared.extend(struct.pack("<f", v.qual))
    shared.extend(struct.pack("<I", (len(alleles) << 16) | len(info_items)))
    shared.extend(struct.pack("<I", (n_fmt << 24) | n_sample))
    write_typed_string(shared, v.id or "")
    for a in alleles:
        write_typed_string(shared, a)
    write_typed_ints(shared, [hdr.string_index(f) for f in v.filters])
    for key, raw in info_items:
        write_typed_ints(shared, [hdr.string_index(key)])
        _encode_info_value(shared, hdr.info.get(key), raw)
    return (
        struct.pack("<II", len(shared), len(fmt_block))
        + bytes(shared)
        + bytes(fmt_block)
    )


def _parse_info(info: str) -> List[Tuple[str, Optional[str]]]:
    if not info or info == ".":
        return []
    out = []
    for item in info.split(";"):
        if "=" in item:
            k, _, val = item.partition("=")
            out.append((k, val))
        else:
            out.append((item, None))
    return out


def _encode_info_value(
    out: bytearray, decl: Optional[_Declared], raw: Optional[str]
) -> None:
    if raw is None:  # Flag
        write_typed_ints(out, [1])
        return
    typ = decl.type if decl else None
    vals = raw.split(",")
    if typ is None:
        typ = _infer_type(vals)
    if typ == "Integer":
        write_typed_ints(
            out, [None if x == "." else int(x) for x in vals]
        )
    elif typ == "Float":
        write_typed_floats(
            out, [None if x == "." else float(x) for x in vals]
        )
    elif typ == "Flag":
        write_typed_ints(out, [1])
    else:  # String / Character: one char vector, commas preserved
        write_typed_string(out, raw)


def _infer_type(vals: List[str]) -> str:
    try:
        for x in vals:
            if x != ".":
                int(x)
        return "Integer"
    except ValueError:
        pass
    try:
        for x in vals:
            if x != ".":
                float(x)
        return "Float"
    except ValueError:
        return "String"


def _encode_genotypes(hdr: BcfHeader, gt_text: str) -> Tuple[bytearray, int]:
    out = bytearray()
    if not gt_text:
        return out, 0
    cols = gt_text.split("\t")
    keys = cols[0].split(":")
    samples = [c.split(":") for c in cols[1:]]
    if len(samples) != hdr.n_samples:
        raise BcfError(
            f"genotype column count {len(samples)} != header samples "
            f"{hdr.n_samples}"
        )
    for ki, key in enumerate(keys):
        write_typed_ints(out, [hdr.string_index(key)])
        fields = [s[ki] if ki < len(s) else "." for s in samples]
        if key == "GT":
            encoded = [_gt_ints(f) for f in fields]
            width = max(len(e) for e in encoded)
            t = _int_type_for([v for e in encoded for v in e])
            fmt, _missing, eov = {
                T_INT8: ("<b", INT8_MISSING, INT8_EOV),
                T_INT16: ("<h", INT16_MISSING, INT16_EOV),
                T_INT32: ("<i", INT32_MISSING, INT32_EOV),
            }[t]
            write_descriptor(out, t, width)
            for e in encoded:
                for v in e:
                    out.extend(struct.pack(fmt, v))
                for _ in range(width - len(e)):
                    out.extend(struct.pack(fmt, eov))
            continue
        decl = hdr.format.get(key)
        typ = decl.type if decl else _infer_type(
            [x for f in fields for x in f.split(",")]
        )
        split = [f.split(",") if f != "." else ["."] for f in fields]
        width = max(len(s) for s in split)
        if typ == "Integer":
            mat = [
                [None if x == "." else int(x) for x in s] for s in split
            ]
            flat = [v for row in mat for v in row if v is not None]
            t = _int_type_for(flat)
            fmt, missing, eov = {
                T_INT8: ("<b", INT8_MISSING, INT8_EOV),
                T_INT16: ("<h", INT16_MISSING, INT16_EOV),
                T_INT32: ("<i", INT32_MISSING, INT32_EOV),
            }[t]
            write_descriptor(out, t, width)
            for row in mat:
                for v in row:
                    out.extend(struct.pack(fmt, missing if v is None else v))
                for _ in range(width - len(row)):
                    out.extend(struct.pack(fmt, eov))
        elif typ == "Float":
            write_descriptor(out, T_FLOAT, width)
            for s in split:
                for x in s:
                    if x == ".":
                        out.extend(struct.pack("<I", FLOAT_MISSING_BITS))
                    else:
                        out.extend(struct.pack("<f", float(x)))
                for _ in range(width - len(s)):
                    out.extend(struct.pack("<I", FLOAT_EOV_BITS))
        else:  # String per sample, NUL-padded to a fixed width
            raws = [f.encode("latin-1") for f in fields]
            width = max(len(r) for r in raws)
            write_descriptor(out, T_CHAR, width)
            for r in raws:
                out.extend(r.ljust(width, b"\x00"))
    return out, len(keys)


def _gt_ints(field: str) -> List[int]:
    """Per the spec a missing GT allele encodes as 0 ((.-allele+1)<<1), so a
    bare '.' field is the single value [0]."""
    if field in (".", ""):
        return [0]
    out: List[int] = []
    phased = False
    for tok in re.split(r"([/|])", field):
        if tok == "|":
            phased = True
        elif tok == "/":
            phased = False
        elif tok:
            allele = 0 if tok == "." else int(tok) + 1
            out.append((allele << 1) | (1 if phased and out else 0))
    return out


# ---------------------------------------------------------------------------
# Whole-payload helpers (uncompressed BCF payload)
# ---------------------------------------------------------------------------


def encode_header(vcf: VcfHeader) -> bytes:
    text = vcf.encode() + b"\x00"
    return MAGIC + struct.pack("<I", len(text)) + text


def decode_header(buf) -> Tuple[BcfHeader, int]:
    """(header, offset of first record) from an uncompressed BCF payload."""
    if bytes(buf[:3]) != b"BCF":
        raise BcfError("not a BCF stream (bad magic)")
    if bytes(buf[3:5]) != b"\x02\x02" and buf[3] != 2:
        raise BcfError(f"unsupported BCF version {buf[3]}.{buf[4]}")
    (l_text,) = struct.unpack_from("<I", buf, 5)
    if len(buf) < 9 + l_text:
        # A truncated buffer must not silently parse as a shorter header
        # (prefix readers grow on this error until the dictionary is whole).
        raise BcfError(
            f"BCF header truncated: need {9 + l_text} bytes, have {len(buf)}"
        )
    text = bytes(buf[9 : 9 + l_text]).rstrip(b"\x00").decode()
    return BcfHeader(VcfHeader.parse(text)), 9 + l_text


def write_bcf(
    stream, vcf: VcfHeader, variants: List[VariantContext]
) -> None:
    """Complete BGZF-compressed BCF file."""
    from . import bgzf

    hdr = BcfHeader(vcf)
    w = bgzf.BgzfWriter(stream, append_terminator=True)
    w.write(encode_header(vcf))
    for v in variants:
        w.write(encode_record(hdr, v))
    w.close()


def read_bcf(path_or_bytes) -> Tuple[BcfHeader, List[BcfVariant]]:
    from . import bgzf

    data = (
        path_or_bytes
        if isinstance(path_or_bytes, (bytes, bytearray))
        else open(path_or_bytes, "rb").read()
    )
    payload = bgzf.decompress_all(data) if bgzf.is_bgzf(data) else data
    hdr, p = decode_header(payload)
    out: List[BcfVariant] = []
    while p + 8 <= len(payload):
        v, p = decode_record(payload, p, hdr)
        out.append(v)
    return hdr, out
