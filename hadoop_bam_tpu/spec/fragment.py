"""SequencedFragment: the FASTQ/QSEQ record model + batched container.

Reference semantics (SequencedFragment.java): a read with sequence + quality
(Sanger Phred+33 text once inside the framework) and 11 nullable Illumina
metadata fields; quality conversion/verification rules from
:229-309 (Sanger offset 33 range [0,93], Illumina offset 64 range [0,62]).

TPU-first addition: ``FragmentBatch`` — the SoA form (padded uint8 seq/qual
tensors + length masks + metadata columns) that ships straight to
ops/quality histograms and base counters.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional

import numpy as np

from ..utils.intervals import FormatError as FormatException

SANGER_OFFSET = 33
SANGER_MAX = 93
ILLUMINA_OFFSET = 64
ILLUMINA_MAX = 62


@dataclass
class SequencedFragment:
    sequence: bytes = b""
    quality: bytes = b""  # text bytes in the *current* encoding
    instrument: Optional[str] = None
    run_number: Optional[int] = None
    flowcell_id: Optional[str] = None
    lane: Optional[int] = None
    tile: Optional[int] = None
    xpos: Optional[int] = None
    ypos: Optional[int] = None
    read: Optional[int] = None
    filter_passed: Optional[bool] = None
    control_number: Optional[int] = None
    index_sequence: Optional[str] = None

    def __str__(self) -> str:
        return f"{self.sequence.decode()}\t{self.quality.decode()}"


def verify_quality(quality: bytes, encoding: str) -> int:
    """Index of first out-of-range byte, -1 if ok (verifyQuality,
    SequencedFragment.java:271-309)."""
    if encoding == "illumina":
        lo, hi = ILLUMINA_OFFSET, ILLUMINA_OFFSET + ILLUMINA_MAX
    elif encoding == "sanger":
        lo, hi = SANGER_OFFSET, SANGER_OFFSET + SANGER_MAX
    else:
        raise ValueError(f"Unsupported base encoding quality {encoding}")
    a = np.frombuffer(quality, dtype=np.uint8)
    bad = (a < lo) | (a > hi)
    idx = np.nonzero(bad)[0]
    return int(idx[0]) if len(idx) else -1


def convert_quality(quality: bytes, current: str, target: str) -> bytes:
    """Range-checked ±31 shift (convertQuality, SequencedFragment.java:229-268)."""
    if current == target:
        raise ValueError(
            f"current and target quality encodings are the same ({current})"
        )
    a = np.frombuffer(quality, dtype=np.uint8).astype(np.int16)
    dist = ILLUMINA_OFFSET - SANGER_OFFSET
    if current == "illumina" and target == "sanger":
        if len(a) and (a.min() < ILLUMINA_OFFSET or a.max() > ILLUMINA_OFFSET + ILLUMINA_MAX):
            bad = int(a[(a < ILLUMINA_OFFSET) | (a > ILLUMINA_OFFSET + ILLUMINA_MAX)][0])
            raise FormatException(
                "base quality score out of range for Illumina Phred+64 format "
                f"(found {bad - ILLUMINA_OFFSET} but acceptable range is "
                f"[0,{ILLUMINA_MAX}]).\nMaybe qualities are encoded in Sanger format?\n"
            )
        return (a - dist).astype(np.uint8).tobytes()
    if current == "sanger" and target == "illumina":
        if len(a) and (a.min() < SANGER_OFFSET or a.max() > SANGER_OFFSET + SANGER_MAX):
            bad = int(a[(a < SANGER_OFFSET) | (a > SANGER_OFFSET + SANGER_MAX)][0])
            raise FormatException(
                "base quality score out of range for Sanger Phred+64 format "
                f"(found {bad - SANGER_OFFSET} but acceptable range is "
                f"[0,{SANGER_MAX}]).\nMaybe qualities are encoded in Illumina format?\n"
            )
        return (a + dist).astype(np.uint8).tobytes()
    raise ValueError(
        f"unsupported BaseQualityEncoding transformation from {current} to {target}"
    )


@dataclass
class FragmentBatch:
    """SoA batch of fragments, device-ready.

    ``seq``/``qual``: uint8[N, Lmax] 0-padded; ``lengths``: int32[N];
    metadata columns are host lists (ragged strings stay host-side).

    ``fragments`` is **lazy**: the vectorized tokenizers build only the SoA
    tensors (the device path never touches record objects); the per-record
    ``SequencedFragment`` view materializes on first access via the
    ``materializer`` the reader installed.
    """

    seq: np.ndarray
    qual: np.ndarray
    lengths: np.ndarray
    _names: Optional[List[str]] = None
    # (buffer, starts, lens) — decode names only when someone asks.
    name_source: Optional[tuple] = None
    _fragments: Optional[List[SequencedFragment]] = None
    materializer: Optional[Callable[["FragmentBatch"], List[SequencedFragment]]] = None

    @property
    def names(self) -> List[str]:
        if self._names is None:
            if self.name_source is None:
                self._names = [""] * self.n_records
            else:
                buf, starts, lens = self.name_source
                mv = memoryview(buf)
                self._names = [
                    str(mv[int(s) : int(s + l)], "utf-8")
                    for s, l in zip(starts, lens)
                ]
        return self._names

    @property
    def fragments(self) -> List[SequencedFragment]:
        if self._fragments is None:
            if self.materializer is not None:
                self._fragments = self.materializer(self)
            else:
                self._fragments = self._default_fragments()
        return self._fragments

    def _default_fragments(self) -> List[SequencedFragment]:
        out = []
        for i in range(self.n_records):
            ln = int(self.lengths[i])
            out.append(
                SequencedFragment(
                    sequence=self.seq[i, :ln].tobytes(),
                    quality=self.qual[i, :ln].tobytes(),
                )
            )
        return out

    @property
    def n_records(self) -> int:
        return len(self.lengths)

    def valid_mask(self) -> np.ndarray:
        L = self.seq.shape[1] if self.seq.ndim == 2 else 0
        return np.arange(L)[None, :] < self.lengths[:, None]

    @staticmethod
    def from_fragments(
        names: List[str], frags: List[SequencedFragment]
    ) -> "FragmentBatch":
        n = len(frags)
        lengths = np.array([len(f.sequence) for f in frags], dtype=np.int32)
        L = int(lengths.max()) if n else 0
        seq = np.zeros((n, L), dtype=np.uint8)
        qual = np.zeros((n, L), dtype=np.uint8)
        for i, f in enumerate(frags):
            seq[i, : len(f.sequence)] = np.frombuffer(f.sequence, np.uint8)
            qual[i, : len(f.quality)] = np.frombuffer(f.quality, np.uint8)
        return FragmentBatch(
            seq=seq, qual=qual, lengths=lengths,
            _names=list(names), _fragments=list(frags),
        )
