"""VCF text format: header model, variant parse/format, key function.

Oracle implementation of the role htsjdk's ``VCFCodec`` plays under the
reference's VCF path.  Genotype columns stay *unparsed* (raw text), the
Lazy{VCF,BCF}GenotypesContext stance (LazyVCFGenotypesContext.java:37-128):
sorting/filtering variants never pays genotype-parse cost.

Key semantics preserved exactly (VCFRecordReader.java:200-204):
``contigIdx << 32 | (start-1)`` with the contig index taken from the
header's ##contig order, falling back to ``(int)murmur3_chars(name)`` for
unknown contigs — including Java's int truncation + sign extension.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..utils.intervals import FormatError as FormatException
from ..utils.murmur3 import murmurhash3_chars


@dataclass
class VcfHeader:
    lines: List[str]  # all '##' meta lines + the '#CHROM' line

    def __post_init__(self):
        self._contigs: List[str] = []
        for ln in self.lines:
            m = re.match(r"##contig=<.*?ID=([^,>]+)", ln)
            if m:
                self._contigs.append(m.group(1))
        self._contig_idx = {c: i for i, c in enumerate(self._contigs)}

    @property
    def contigs(self) -> List[str]:
        return self._contigs

    def contig_index(self, name: str) -> int:
        """Header contig index, or Java (int)murmur3 for unknown contigs
        (VCFRecordReader.java:200-202)."""
        idx = self._contig_idx.get(name)
        if idx is not None:
            return idx
        h = murmurhash3_chars(name, 0) & 0xFFFFFFFF
        return h - (1 << 32) if h >= 1 << 31 else h

    @property
    def samples(self) -> List[str]:
        for ln in self.lines:
            if ln.startswith("#CHROM"):
                cols = ln.split("\t")
                return cols[9:] if len(cols) > 9 else []
        return []

    def encode(self) -> bytes:
        return ("\n".join(self.lines) + "\n").encode()

    @staticmethod
    def parse(text_or_lines) -> "VcfHeader":
        if isinstance(text_or_lines, (bytes, str)):
            if isinstance(text_or_lines, bytes):
                text_or_lines = text_or_lines.decode()
            lines = [l for l in text_or_lines.split("\n") if l.startswith("#")]
        else:
            lines = list(text_or_lines)
        if not any(l.startswith("##fileformat") for l in lines):
            raise FormatException("missing ##fileformat header line")
        return VcfHeader(lines)


_MISSING_QUAL = None


@dataclass
class VariantContext:
    """One VCF site; genotype columns kept as raw text (lazy)."""

    chrom: str
    pos: int  # 1-based
    id: str
    ref: str
    alts: List[str]
    qual: Optional[float]
    filters: List[str]  # empty == missing ('.'); ['PASS'] == passed
    info: str  # raw INFO column
    genotypes_raw: str = ""  # FORMAT + sample columns, untouched

    @property
    def start(self) -> int:
        return self.pos

    @property
    def end(self) -> int:
        """END info key if present, else pos + len(ref) - 1 (htsjdk rule)."""
        m = re.search(r"(?:^|;)END=(-?\d+)(?:;|$)", self.info)
        if m:
            return int(m.group(1))
        return self.pos + len(self.ref) - 1

    def format_line(self) -> str:
        qual = (
            "."
            if self.qual is None
            else (f"{self.qual:g}" if self.qual % 1 else str(int(self.qual)))
        )
        filt = ";".join(self.filters) if self.filters else "."
        alt = ",".join(self.alts) if self.alts else "."
        base = "\t".join(
            [
                self.chrom,
                str(self.pos),
                self.id or ".",
                self.ref,
                alt,
                qual,
                filt,
                self.info or ".",
            ]
        )
        if self.genotypes_raw:
            base += "\t" + self.genotypes_raw
        return base


def parse_variant_line(line: str) -> VariantContext:
    fields = line.rstrip("\n").split("\t")
    if len(fields) < 8:
        raise FormatException(
            f"VCF data line has {len(fields)} fields (need >= 8): {line[:80]!r}"
        )
    chrom, pos_s, vid, ref, alt, qual_s, filt, info = fields[:8]
    if not chrom or not ref:
        raise FormatException(f"empty CHROM/REF in line {line[:80]!r}")
    try:
        pos = int(pos_s)
    except ValueError:
        raise FormatException(f"non-integer POS {pos_s!r}")
    if qual_s == "." or qual_s == "":
        qual = None
    else:
        try:
            qual = float(qual_s)
        except ValueError:
            raise FormatException(f"non-numeric QUAL {qual_s!r}")
    alts = [] if alt in (".", "") else alt.split(",")
    for a in alts:
        # Symbolic alleles (<DEL>, <INS:ME>…) and breakend notation allow
        # arbitrary letters in their IDs / mate coordinates (VCF 4.2
        # §1.4.5); plain tokens stay restricted to base strings.
        if re.search(r"[<>\[\]:]", a):
            ok = re.fullmatch(r"[A-Za-z0-9_.:<>\[\]=*-]+", a)
        else:
            ok = re.fullmatch(r"[ACGTNacgtn*.0-9_=-]+", a)
        if not ok:
            raise FormatException(f"malformed ALT allele {a!r}")
    filters = [] if filt in (".", "") else filt.split(";")
    genotypes_raw = "\t".join(fields[8:]) if len(fields) > 8 else ""
    return VariantContext(
        chrom=chrom,
        pos=pos,
        id="" if vid == "." else vid,
        ref=ref,
        alts=alts,
        qual=qual,
        filters=filters,
        info=info,
        genotypes_raw=genotypes_raw,
    )


def variant_key(header: VcfHeader, v: VariantContext) -> int:
    """``contigIdx << 32 | (start-1)`` with Java sign extension
    (VCFRecordReader.java:200-204)."""
    idx = header.contig_index(v.chrom)
    lo = v.start - 1
    lo64 = lo & 0xFFFFFFFFFFFFFFFF if lo < 0 else lo
    k = ((idx << 32) | lo64) & 0xFFFFFFFFFFFFFFFF
    return k - (1 << 64) if k >= 1 << 63 else k


def read_vcf(text_or_bytes) -> Tuple[VcfHeader, List[VariantContext]]:
    text = (
        text_or_bytes.decode()
        if isinstance(text_or_bytes, bytes)
        else text_or_bytes
    )
    header_lines = []
    variants = []
    for line in text.split("\n"):
        if not line:
            continue
        if line.startswith("#"):
            header_lines.append(line)
        else:
            variants.append(parse_variant_line(line))
    return VcfHeader(header_lines), variants
