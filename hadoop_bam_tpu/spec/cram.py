"""CRAM container structure: magic, ITF8/LTF8 varints, container headers.

The structural layer the reference uses for split planning — its
CRAMInputFormat collects container start offsets by iterating container
headers (CRAMInputFormat.java:58-70 via htsjdk's CramContainerIterator) and
snaps splits to them.  This module parses the CRAM 2.1/3.x framing: file
definition, container header fields, and the EOF container detection.

Record-level decode (core/external blocks, entropy codecs) is intentionally
not implemented yet — containers are planned/counted here, and readers
surface a clear capability error (SURVEY.md §7 stage 8 defers CRAM codec
breadth; the container header's nRecords already supports counting).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Optional, Tuple

MAGIC = b"CRAM"
FILE_DEFINITION_LEN = 26  # magic + 2 version bytes + 20-byte file id


class CramError(IOError):
    pass


def read_itf8(buf: bytes, pos: int) -> Tuple[int, int]:
    """CRAM ITF8 varint → (value, new_pos)."""
    b0 = buf[pos]
    if b0 < 0x80:
        return b0, pos + 1
    if b0 < 0xC0:
        return ((b0 & 0x7F) << 8) | buf[pos + 1], pos + 2
    if b0 < 0xE0:
        return ((b0 & 0x3F) << 16) | (buf[pos + 1] << 8) | buf[pos + 2], pos + 3
    if b0 < 0xF0:
        return (
            ((b0 & 0x1F) << 24)
            | (buf[pos + 1] << 16)
            | (buf[pos + 2] << 8)
            | buf[pos + 3]
        ), pos + 4
    v = (
        ((b0 & 0x0F) << 28)
        | (buf[pos + 1] << 20)
        | (buf[pos + 2] << 12)
        | (buf[pos + 3] << 4)
        | (buf[pos + 4] & 0x0F)
    )
    # sign: ITF8 carries int32 values
    if v >= 1 << 31:
        v -= 1 << 32
    return v, pos + 5


def read_ltf8(buf: bytes, pos: int) -> Tuple[int, int]:
    """CRAM LTF8 varint (int64) → (value, new_pos)."""
    b0 = buf[pos]
    n_extra = 0
    probe = 0x80
    while n_extra < 8 and b0 & probe:
        n_extra += 1
        probe >>= 1
    if n_extra == 0:
        return b0, pos + 1
    if n_extra < 8:
        v = b0 & (0xFF >> (n_extra + 1))
    else:
        v = 0
    for i in range(n_extra):
        v = (v << 8) | buf[pos + 1 + i]
    if v >= 1 << 63:
        v -= 1 << 64
    return v, pos + 1 + n_extra


@dataclass
class ContainerHeader:
    offset: int  # file offset of this container
    length: int  # byte length of the container's block data
    ref_seq_id: int
    start_pos: int
    align_span: int
    n_records: int
    record_counter: int
    bases: int
    n_blocks: int
    landmarks: List[int]
    header_size: int  # bytes consumed by this header itself

    @property
    def next_offset(self) -> int:
        return self.offset + self.header_size + self.length

    @property
    def is_eof(self) -> bool:
        """EOF container: ref -1, 0 records/blocks... htsjdk detects via
        content; the spec's v3 EOF has ref_seq_id=-1 and n_records=0."""
        return self.ref_seq_id == -1 and self.n_records == 0 and self.n_blocks <= 1


def parse_file_definition(data: bytes) -> Tuple[int, int]:
    """Returns (major, minor) version; validates the magic."""
    if data[:4] != MAGIC:
        raise CramError("missing CRAM magic")
    return data[4], data[5]


def parse_container_header(
    data: bytes, pos: int, major: int
) -> ContainerHeader:
    start = pos
    if pos + 4 > len(data):
        raise CramError(f"truncated container header at {pos}")
    (length,) = struct.unpack_from("<i", data, pos)
    pos += 4
    ref_seq_id, pos = read_itf8(data, pos)
    start_pos, pos = read_itf8(data, pos)
    align_span, pos = read_itf8(data, pos)
    n_records, pos = read_itf8(data, pos)
    record_counter, pos = read_ltf8(data, pos)
    bases, pos = read_ltf8(data, pos)
    n_blocks, pos = read_itf8(data, pos)
    n_landmarks, pos = read_itf8(data, pos)
    landmarks = []
    for _ in range(n_landmarks):
        lm, pos = read_itf8(data, pos)
        landmarks.append(lm)
    if major >= 3:
        pos += 4  # crc32
    return ContainerHeader(
        offset=start,
        length=length,
        ref_seq_id=ref_seq_id,
        start_pos=start_pos,
        align_span=align_span,
        n_records=n_records,
        record_counter=record_counter,
        bases=bases,
        n_blocks=n_blocks,
        landmarks=landmarks,
        header_size=pos - start,
    )


def iter_containers(data: bytes) -> List[ContainerHeader]:
    """All container headers incl. the EOF container (CramContainerIterator
    equivalent)."""
    major, _ = parse_file_definition(data)
    out: List[ContainerHeader] = []
    pos = FILE_DEFINITION_LEN
    while pos < len(data):
        hdr = parse_container_header(data, pos, major)
        out.append(hdr)
        pos = hdr.next_offset
    if pos != len(data):
        raise CramError("container chain misaligned")
    return out


def container_offsets(data: bytes) -> List[int]:
    """Start offsets of data containers (first = the CRAM header container)."""
    return [c.offset for c in iter_containers(data)]
