"""CRAM format: framing, containers, blocks, slices, record codec.

The role htsjdk's CRAM stack plays below the reference's CRAMInputFormat /
CRAMRecordReader / CRAMRecordWriter (CRAMInputFormat.java:58-80,
CRAMRecordReader.java:43-88, CRAMRecordWriter.java:49-121): container
iteration for split planning, record decode for reading (CRAM 2.1 and 3.0,
reference-based and no-ref), and container emission for writing (3.0,
external encodings, detached mates, no-ref bases).
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

MAGIC = b"CRAM"
FILE_DEFINITION_LEN = 26  # magic + 2 version bytes + 20-byte file id


class CramError(IOError):
    pass


def read_itf8(buf: bytes, pos: int) -> Tuple[int, int]:
    """CRAM ITF8 varint → (value, new_pos)."""
    b0 = buf[pos]
    if b0 < 0x80:
        return b0, pos + 1
    if b0 < 0xC0:
        return ((b0 & 0x7F) << 8) | buf[pos + 1], pos + 2
    if b0 < 0xE0:
        return ((b0 & 0x3F) << 16) | (buf[pos + 1] << 8) | buf[pos + 2], pos + 3
    if b0 < 0xF0:
        return (
            ((b0 & 0x1F) << 24)
            | (buf[pos + 1] << 16)
            | (buf[pos + 2] << 8)
            | buf[pos + 3]
        ), pos + 4
    v = (
        ((b0 & 0x0F) << 28)
        | (buf[pos + 1] << 20)
        | (buf[pos + 2] << 12)
        | (buf[pos + 3] << 4)
        | (buf[pos + 4] & 0x0F)
    )
    # sign: ITF8 carries int32 values
    if v >= 1 << 31:
        v -= 1 << 32
    return v, pos + 5


def read_ltf8(buf: bytes, pos: int) -> Tuple[int, int]:
    """CRAM LTF8 varint (int64) → (value, new_pos)."""
    b0 = buf[pos]
    n_extra = 0
    probe = 0x80
    while n_extra < 8 and b0 & probe:
        n_extra += 1
        probe >>= 1
    if n_extra == 0:
        return b0, pos + 1
    if n_extra < 8:
        v = b0 & (0xFF >> (n_extra + 1))
    else:
        v = 0
    for i in range(n_extra):
        v = (v << 8) | buf[pos + 1 + i]
    if v >= 1 << 63:
        v -= 1 << 64
    return v, pos + 1 + n_extra


@dataclass
class ContainerHeader:
    offset: int  # file offset of this container
    length: int  # byte length of the container's block data
    ref_seq_id: int
    start_pos: int
    align_span: int
    n_records: int
    record_counter: int
    bases: int
    n_blocks: int
    landmarks: List[int]
    header_size: int  # bytes consumed by this header itself

    @property
    def next_offset(self) -> int:
        return self.offset + self.header_size + self.length

    @property
    def is_eof(self) -> bool:
        """EOF container: ref -1, 0 records/blocks... htsjdk detects via
        content; the spec's v3 EOF has ref_seq_id=-1 and n_records=0."""
        return self.ref_seq_id == -1 and self.n_records == 0 and self.n_blocks <= 1


def parse_file_definition(data: bytes) -> Tuple[int, int]:
    """Returns (major, minor) version; validates the magic."""
    if data[:4] != MAGIC:
        raise CramError("missing CRAM magic")
    return data[4], data[5]


def parse_container_header(
    data: bytes, pos: int, major: int
) -> ContainerHeader:
    start = pos
    if pos + 4 > len(data):
        raise CramError(f"truncated container header at {pos}")
    (length,) = struct.unpack_from("<i", data, pos)
    pos += 4
    ref_seq_id, pos = read_itf8(data, pos)
    start_pos, pos = read_itf8(data, pos)
    align_span, pos = read_itf8(data, pos)
    n_records, pos = read_itf8(data, pos)
    record_counter, pos = read_ltf8(data, pos)
    bases, pos = read_ltf8(data, pos)
    n_blocks, pos = read_itf8(data, pos)
    n_landmarks, pos = read_itf8(data, pos)
    landmarks = []
    for _ in range(n_landmarks):
        lm, pos = read_itf8(data, pos)
        landmarks.append(lm)
    if major >= 3:
        pos += 4  # crc32
    return ContainerHeader(
        offset=start,
        length=length,
        ref_seq_id=ref_seq_id,
        start_pos=start_pos,
        align_span=align_span,
        n_records=n_records,
        record_counter=record_counter,
        bases=bases,
        n_blocks=n_blocks,
        landmarks=landmarks,
        header_size=pos - start,
    )


def iter_containers(data: bytes) -> List[ContainerHeader]:
    """All container headers incl. the EOF container (CramContainerIterator
    equivalent)."""
    major, _ = parse_file_definition(data)
    out: List[ContainerHeader] = []
    pos = FILE_DEFINITION_LEN
    while pos < len(data):
        hdr = parse_container_header(data, pos, major)
        out.append(hdr)
        pos = hdr.next_offset
    if pos != len(data):
        raise CramError("container chain misaligned")
    return out


def container_offsets(data: bytes) -> List[int]:
    """Start offsets of data containers (first = the CRAM header container)."""
    return [c.offset for c in iter_containers(data)]


# ---------------------------------------------------------------------------
# Varint writers
# ---------------------------------------------------------------------------


def write_itf8(v: int) -> bytes:
    v &= 0xFFFFFFFF
    if v < 0x80:
        return bytes([v])
    if v < 0x4000:
        return bytes([0x80 | (v >> 8), v & 0xFF])
    if v < 0x200000:
        return bytes([0xC0 | (v >> 16), (v >> 8) & 0xFF, v & 0xFF])
    if v < 0x10000000:
        return bytes(
            [0xE0 | (v >> 24), (v >> 16) & 0xFF, (v >> 8) & 0xFF, v & 0xFF]
        )
    return bytes(
        [
            0xF0 | (v >> 28),
            (v >> 20) & 0xFF,
            (v >> 12) & 0xFF,
            (v >> 4) & 0xFF,
            v & 0x0F,
        ]
    )


def write_ltf8(v: int) -> bytes:
    """n leading 1-bits in the first byte announce n extra bytes; the first
    byte's low ``7-n`` bits carry the value's top bits (read_ltf8 inverse)."""
    v &= 0xFFFFFFFFFFFFFFFF
    for n_extra in range(8):
        if v < 1 << (7 + 7 * n_extra):
            ones = (0xFF << (8 - n_extra)) & 0xFF
            b0 = ones | (v >> (8 * n_extra))
            rest = [(v >> (8 * i)) & 0xFF for i in range(n_extra - 1, -1, -1)]
            return bytes([b0] + rest)
    return bytes([0xFF]) + v.to_bytes(8, "big")


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------

CT_FILE_HEADER = 0
CT_COMPRESSION_HEADER = 1
CT_SLICE_HEADER = 2
CT_EXTERNAL = 4
CT_CORE = 5


@dataclass
class BlockFrame:
    """One block's parsed frame with the payload still compressed — the
    split that lets ``decode_container`` batch every block of a
    container through one ``cram_codecs.decompress_batch`` call (the
    rANS-lanes seam) instead of inflating inline one at a time."""

    method: int
    content_type: int
    content_id: int
    payload: bytes
    raw_size: int


@dataclass
class Block:
    method: int
    content_type: int
    content_id: int
    raw: bytes  # uncompressed payload

    @staticmethod
    def read_frame(
        data: bytes, pos: int, major: int
    ) -> Tuple[BlockFrame, int]:
        method = data[pos]
        ctype = data[pos + 1]
        pos += 2
        cid, pos = read_itf8(data, pos)
        csize, pos = read_itf8(data, pos)
        rsize, pos = read_itf8(data, pos)
        payload = data[pos : pos + csize]
        if len(payload) != csize:
            raise CramError("truncated block")
        pos += csize
        if major >= 3:
            pos += 4  # crc32
        return BlockFrame(method, ctype, cid, payload, rsize), pos

    @staticmethod
    def finish(frame: BlockFrame, raw: bytes) -> "Block":
        if len(raw) != frame.raw_size:
            raise CramError(
                f"block inflates to {len(raw)}, declared {frame.raw_size}"
            )
        return Block(frame.method, frame.content_type, frame.content_id, raw)

    @staticmethod
    def read(data: bytes, pos: int, major: int) -> Tuple["Block", int]:
        from . import cram_codecs

        frame, pos = Block.read_frame(data, pos, major)
        raw = cram_codecs.decompress(
            frame.method, frame.payload, frame.raw_size
        )
        return Block.finish(frame, raw), pos

    def write(self, major: int, method: Optional[int] = None) -> bytes:
        from . import cram_codecs

        m = self.method if method is None else method
        comp = cram_codecs.compress(m, self.raw)
        if len(comp) >= len(self.raw) and m != 0:
            m, comp = 0, self.raw  # store raw when compression doesn't pay
        out = bytearray()
        out.append(m)
        out.append(self.content_type)
        out += write_itf8(self.content_id)
        out += write_itf8(len(comp))
        out += write_itf8(len(self.raw))
        out += comp
        if major >= 3:
            out += struct.pack("<I", zlib.crc32(bytes(out)))
        return bytes(out)


# ---------------------------------------------------------------------------
# Compression header
# ---------------------------------------------------------------------------

_BASES = b"ACGTN"
_DEFAULT_SUB = bytes([0x1B, 0x1B, 0x1B, 0x1B, 0x1B])  # identity ranking


def _sub_code_to_base(matrix: bytes, ref_base: int) -> Dict[int, int]:
    """code (0..3) → substituted base, for one reference base."""
    try:
        r = _BASES.index(ref_base)
    except ValueError:
        r = 4
    alts = [b for b in _BASES if b != _BASES[r]] if r < 5 else list(_BASES[:4])
    byte = matrix[r]
    out = {}
    for alt_idx, alt in enumerate(alts):
        code = (byte >> (6 - 2 * alt_idx)) & 3
        out[code] = alt
    return out


class CompressionHeader:
    """Preservation map + data-series/tag encoding maps."""

    def __init__(self):
        self.rn_preserved = True
        self.ap_delta = True
        self.rr_required = True
        self.sub_matrix = _DEFAULT_SUB
        self.td: List[List[Tuple[bytes, int]]] = [[]]  # [(2-byte tag, type)]
        self.encodings: Dict[str, "object"] = {}
        self.tag_encodings: Dict[int, "object"] = {}

    @staticmethod
    def parse(raw: bytes) -> "CompressionHeader":
        from .cram_codecs import parse_encoding

        ch = CompressionHeader()
        pos = 0
        # preservation map
        _size, pos = read_itf8(raw, pos)
        nmap, pos = read_itf8(raw, pos)
        for _ in range(nmap):
            key = raw[pos : pos + 2].decode("latin-1")
            pos += 2
            if key == "RN":
                ch.rn_preserved = raw[pos] != 0
                pos += 1
            elif key == "AP":
                ch.ap_delta = raw[pos] != 0
                pos += 1
            elif key == "RR":
                ch.rr_required = raw[pos] != 0
                pos += 1
            elif key == "SM":
                ch.sub_matrix = bytes(raw[pos : pos + 5])
                pos += 5
            elif key == "TD":
                ln, pos = read_itf8(raw, pos)
                blob = bytes(raw[pos : pos + ln])
                pos += ln
                ch.td = []
                for line in blob.split(b"\x00")[:-1] if blob.endswith(b"\x00") else blob.split(b"\x00"):
                    entries = [
                        (line[i : i + 2], line[i + 2])
                        for i in range(0, len(line), 3)
                    ]
                    ch.td.append(entries)
                if not ch.td:
                    ch.td = [[]]
            else:
                raise CramError(f"unknown preservation key {key!r}")
        # data series encodings
        _size, pos = read_itf8(raw, pos)
        nenc, pos = read_itf8(raw, pos)
        for _ in range(nenc):
            key = raw[pos : pos + 2].decode("latin-1")
            pos += 2
            enc, pos = parse_encoding(raw, pos)
            ch.encodings[key] = enc
        # tag encodings
        _size, pos = read_itf8(raw, pos)
        ntag, pos = read_itf8(raw, pos)
        for _ in range(ntag):
            key, pos = read_itf8(raw, pos)
            enc, pos = parse_encoding(raw, pos)
            ch.tag_encodings[key] = enc
        return ch

    def series(self, key: str):
        enc = self.encodings.get(key)
        if enc is None:
            raise CramError(f"no encoding for data series {key}")
        return enc


# ---------------------------------------------------------------------------
# Slice header
# ---------------------------------------------------------------------------


@dataclass
class SliceHeader:
    ref_seq_id: int
    start: int
    span: int
    n_records: int
    record_counter: int
    n_blocks: int
    content_ids: List[int]
    embedded_ref_id: int
    md5: bytes

    @staticmethod
    def parse(raw: bytes, major: int) -> "SliceHeader":
        pos = 0
        ref_seq_id, pos = read_itf8(raw, pos)
        start, pos = read_itf8(raw, pos)
        span, pos = read_itf8(raw, pos)
        n_records, pos = read_itf8(raw, pos)
        if major >= 3:
            counter, pos = read_ltf8(raw, pos)
        else:
            counter, pos = read_itf8(raw, pos)
        n_blocks, pos = read_itf8(raw, pos)
        nids, pos = read_itf8(raw, pos)
        ids = []
        for _ in range(nids):
            v, pos = read_itf8(raw, pos)
            ids.append(v)
        emb, pos = read_itf8(raw, pos)
        md5 = bytes(raw[pos : pos + 16])
        return SliceHeader(
            ref_seq_id, start, span, n_records, counter, n_blocks, ids, emb, md5
        )

    def encode(self, major: int) -> bytes:
        out = bytearray()
        out += write_itf8(self.ref_seq_id)
        out += write_itf8(self.start)
        out += write_itf8(self.span)
        out += write_itf8(self.n_records)
        out += (write_ltf8 if major >= 3 else write_itf8)(self.record_counter)
        out += write_itf8(self.n_blocks)
        out += write_itf8(len(self.content_ids))
        for cid in self.content_ids:
            out += write_itf8(cid)
        out += write_itf8(self.embedded_ref_id)
        out += self.md5
        return bytes(out)


# ---------------------------------------------------------------------------
# EOF containers (spec constants)
# ---------------------------------------------------------------------------

EOF_V3 = bytes.fromhex(
    "0f000000ffffffff0fe0454f460000000000010005bdd94f"
    "0001000606010001000100ee63014b"
)
EOF_V2 = bytes.fromhex(
    "0b000000ffffffffffe0454f4600000000010000010006"
    "06010001000100"
)


def is_eof_marker(data: bytes, pos: int) -> bool:
    rest = data[pos:]
    return rest == EOF_V3 or rest == EOF_V2


# ---------------------------------------------------------------------------
# Record decode
# ---------------------------------------------------------------------------

# CRAM record flags (CF)
CF_QS_STORED = 0x1
CF_DETACHED = 0x2
CF_MATE_DOWNSTREAM = 0x4
CF_NO_SEQ = 0x8  # v3: unknown bases

# CRAM mate flags (MF)
MF_MATE_NEG_STRAND = 0x1
MF_MATE_UNMAPPED = 0x2

from .bam import (  # noqa: E402  (cycle-free: bam does not import cram)
    BamRecord,
    build_record,
    FLAG_MATE_REVERSE,
    FLAG_MATE_UNMAPPED,
    FLAG_REVERSE,
    FLAG_UNMAPPED,
)


@dataclass
class _CramRec:
    bf: int = 0
    cf: int = 0
    refid: int = -1
    rl: int = 0
    ap: int = 0  # 1-based
    rg: int = -1
    name: bytes = b""
    mf: int = 0
    ns: int = -1
    np: int = 0
    ts: int = 0
    nf: int = -1
    tags: bytes = b""
    features: List[Tuple[int, str, object]] = field(default_factory=list)
    mq: int = 0
    quals: bytes = b""
    bases: object = b""  # reconstructed (bytes or str)
    _cigar: Optional[List[Tuple[int, str]]] = None


def _decode_slice_records(
    major: int,
    comp: CompressionHeader,
    sh: SliceHeader,
    ctx,
    ref_getter: Optional[Callable[[int], bytes]],
) -> List[BamRecord]:
    E = comp.series
    recs: List[_CramRec] = []
    prev_ap = sh.start
    for rec_i in range(sh.n_records):
        r = _CramRec()
        if not comp.rn_preserved:
            # deterministic generated name from the global record counter
            # (htslib lossy-names behavior); mates are renamed to match
            # during NF linking below
            r.name = str(sh.record_counter + rec_i).encode()
        r.bf = E("BF").read_int(ctx)
        r.cf = E("CF").read_int(ctx)
        r.refid = (
            E("RI").read_int(ctx) if sh.ref_seq_id == -2 else sh.ref_seq_id
        )
        r.rl = E("RL").read_int(ctx)
        if comp.ap_delta:
            r.ap = prev_ap + E("AP").read_int(ctx)
            prev_ap = r.ap
        else:
            r.ap = E("AP").read_int(ctx)
        r.rg = E("RG").read_int(ctx)
        if comp.rn_preserved:
            r.name = E("RN").read_bytes(ctx)
        if r.cf & CF_DETACHED:
            r.mf = E("MF").read_int(ctx)
            if not comp.rn_preserved:
                r.name = E("RN").read_bytes(ctx)
            r.ns = E("NS").read_int(ctx)
            r.np = E("NP").read_int(ctx)
            r.ts = E("TS").read_int(ctx)
        elif r.cf & CF_MATE_DOWNSTREAM:
            r.nf = E("NF").read_int(ctx)
        # tags
        tl = E("TL").read_int(ctx)
        if "TL" not in comp.encodings and ("TC" in comp.encodings):
            raise CramError("CRAM 2.0 TC/TN tag layout not supported")
        tag_bytes = bytearray()
        for tag, ttype in comp.td[tl]:
            key = (tag[0] << 16) | (tag[1] << 8) | ttype
            enc = comp.tag_encodings.get(key)
            if enc is None:
                raise CramError(f"no tag encoding for {tag}:{chr(ttype)}")
            val = enc.read_bytes(ctx)
            tag_bytes += tag + bytes([ttype]) + val
        r.tags = bytes(tag_bytes)
        if not (r.bf & FLAG_UNMAPPED):
            fn = E("FN").read_int(ctx)
            fpos = 0
            for _f in range(fn):
                fc = chr(E("FC").read_byte(ctx))
                fpos += E("FP").read_int(ctx)
                if fc == "X":
                    payload: object = E("BS").read_byte(ctx)
                elif fc == "I":
                    payload = E("IN").read_bytes(ctx)
                elif fc == "S":
                    payload = E("SC").read_bytes(ctx)
                elif fc == "b":
                    payload = E("BB").read_bytes(ctx)
                elif fc == "q":
                    payload = E("QQ").read_bytes(ctx)
                elif fc == "B":
                    payload = (
                        E("BA").read_byte(ctx),
                        E("QS").read_byte(ctx),
                    )
                elif fc == "i":
                    payload = E("BA").read_byte(ctx)
                elif fc == "Q":
                    payload = E("QS").read_byte(ctx)
                elif fc == "D":
                    payload = E("DL").read_int(ctx)
                elif fc == "N":
                    payload = E("RS").read_int(ctx)
                elif fc == "H":
                    payload = E("HC").read_int(ctx)
                elif fc == "P":
                    payload = E("PD").read_int(ctx)
                else:
                    raise CramError(f"unknown feature code {fc!r}")
                r.features.append((fpos, fc, payload))
            r.mq = E("MQ").read_int(ctx)
            if r.cf & CF_QS_STORED:
                r.quals = E("QS").read_byte_run(ctx, r.rl)
            if not comp.rr_required:
                # no-ref mode drains the BA series *inside* the record's
                # decode turn (htslib cram_decode_seq ordering)
                r.bases, r._cigar = _reconstruct_mapped(
                    r, comp, ctx, ref_getter
                )
        else:
            if not (r.cf & CF_NO_SEQ):
                r.bases = E("BA").read_byte_run(ctx, r.rl)
            if r.cf & CF_QS_STORED:
                r.quals = E("QS").read_byte_run(ctx, r.rl)
        recs.append(r)

    # mate linking within the slice (non-detached pairs)
    for i, r in enumerate(recs):
        if r.nf >= 0:
            j = i + r.nf + 1
            if j >= len(recs):
                raise CramError("NF mate index out of slice")
            m = recs[j]
            if not comp.rn_preserved:
                m.name = r.name  # mates share the generated name
            r.ns, r.np, m.ns, m.np = m.refid, m.ap, r.refid, r.ap
            if m.bf & FLAG_REVERSE:
                r.mf |= MF_MATE_NEG_STRAND
            if m.bf & FLAG_UNMAPPED:
                r.mf |= MF_MATE_UNMAPPED
            if r.bf & FLAG_REVERSE:
                m.mf |= MF_MATE_NEG_STRAND
            if r.bf & FLAG_UNMAPPED:
                m.mf |= MF_MATE_UNMAPPED
            # template span: leftmost positive, rightmost negative
            left, right = (r, m) if r.ap <= m.ap else (m, r)
            span = (
                right.ap
                + _read_span_from_features(right)
                - 1
                - left.ap
                + 1
            )
            left.ts, right.ts = span, -span

    out: List[BamRecord] = []
    for r in recs:
        out.append(_to_bam(r, comp, ctx, ref_getter))
    return out


def _read_span_from_features(r: _CramRec) -> int:
    span = r.rl
    for _pos, fc, payload in r.features:
        if fc == "I":
            span -= len(payload)  # type: ignore[arg-type]
        elif fc == "i":
            span -= 1
        elif fc == "S":
            span -= len(payload)  # type: ignore[arg-type]
        elif fc == "D" or fc == "N":
            span += int(payload)  # type: ignore[arg-type]
    return max(span, 1)


def _to_bam(
    r: _CramRec,
    comp: CompressionHeader,
    ctx,
    ref_getter: Optional[Callable[[int], bytes]],
) -> BamRecord:
    flag = r.bf
    if r.mf & MF_MATE_NEG_STRAND:
        flag |= FLAG_MATE_REVERSE
    if r.mf & MF_MATE_UNMAPPED:
        flag |= FLAG_MATE_UNMAPPED
    name = r.name.decode("latin-1")
    if r.bf & FLAG_UNMAPPED:
        seq = r.bases.decode("latin-1") if r.bases else "*"
        qual = r.quals if r.quals else b""
        rec = build_record(
            name=name,
            refid=r.refid,
            pos=r.ap - 1,
            mapq=r.mq,
            flag=flag,
            cigar=[],
            seq=seq,
            qual=qual,
            next_refid=r.ns,
            next_pos=r.np - 1,
            tlen=r.ts,
            tags=r.tags,
        )
        return rec
    if r._cigar is not None:  # no-ref: already reconstructed inline
        seq, cigar = r.bases, r._cigar
    else:
        seq, cigar = _reconstruct_mapped(r, comp, ctx, ref_getter)
    return build_record(
        name=name,
        refid=r.refid,
        pos=r.ap - 1,
        mapq=r.mq,
        flag=flag,
        cigar=cigar,
        seq=seq,
        qual=r.quals,
        next_refid=r.ns,
        next_pos=r.np - 1,
        tlen=r.ts,
        tags=r.tags,
    )


def _reconstruct_mapped(
    r: _CramRec,
    comp: CompressionHeader,
    ctx,
    ref_getter: Optional[Callable[[int], bytes]],
):
    """Features + (reference | BA series) → (seq, cigar).

    Mirrors the reference-based reconstruction of htslib's cram_decode_seq:
    positions not covered by features come from the reference when RR=true,
    from the BA data series when RR=false (no-ref mode).
    """
    E = comp.series
    bases = bytearray(b"N" * r.rl)
    covered = bytearray(r.rl)  # 1 = provided by a feature
    cigar_ops: List[Tuple[int, str]] = []
    ref = None
    if comp.rr_required:
        if ref_getter is None:
            raise CramError(
                "CRAM slice requires the reference; configure "
                "hadoopbam.cram.reference-source-path"
            )
        ref = ref_getter(r.refid)

    def push(op: str, n: int) -> None:
        if n <= 0:
            return
        if cigar_ops and cigar_ops[-1][1] == op:
            cigar_ops[-1] = (cigar_ops[-1][0] + n, op)
        else:
            cigar_ops.append((n, op))

    rpos = 0  # read cursor (0-based)
    ref_cursor = r.ap - 1  # 0-based reference position
    sub_cache: Dict[int, Dict[int, int]] = {}
    for fpos, fc, payload in sorted(r.features, key=lambda t: t[0]):
        gap = (fpos - 1) - rpos
        if gap > 0:
            _fill_match(bases, covered, rpos, gap, ref, ref_cursor)
            push("M", gap)
            rpos += gap
            ref_cursor += gap
        if fc == "S":
            sc = payload  # type: ignore[assignment]
            bases[rpos : rpos + len(sc)] = sc
            for k in range(len(sc)):
                covered[rpos + k] = 1
            push("S", len(sc))
            rpos += len(sc)
        elif fc == "X":
            ref_base = ref[ref_cursor] if ref is not None else ord("N")
            ref_base = _upper(ref_base)
            codes = sub_cache.get(ref_base)
            if codes is None:
                codes = _sub_code_to_base(comp.sub_matrix, ref_base)
                sub_cache[ref_base] = codes
            bases[rpos] = codes.get(int(payload), ord("N"))  # type: ignore[arg-type]
            covered[rpos] = 1
            push("M", 1)
            rpos += 1
            ref_cursor += 1
        elif fc == "I":
            ins = payload  # type: ignore[assignment]
            bases[rpos : rpos + len(ins)] = ins
            for k in range(len(ins)):
                covered[rpos + k] = 1
            push("I", len(ins))
            rpos += len(ins)
        elif fc == "i":
            bases[rpos] = int(payload)  # type: ignore[arg-type]
            covered[rpos] = 1
            push("I", 1)
            rpos += 1
        elif fc == "b":
            bb = payload  # type: ignore[assignment]
            bases[rpos : rpos + len(bb)] = bb
            for k in range(len(bb)):
                covered[rpos + k] = 1
            push("M", len(bb))
            rpos += len(bb)
            ref_cursor += len(bb)
        elif fc == "B":
            b, _q = payload  # type: ignore[misc]
            bases[rpos] = b
            covered[rpos] = 1
            push("M", 1)
            rpos += 1
            ref_cursor += 1
        elif fc == "D":
            push("D", int(payload))  # type: ignore[arg-type]
            ref_cursor += int(payload)  # type: ignore[arg-type]
        elif fc == "N":
            push("N", int(payload))  # type: ignore[arg-type]
            ref_cursor += int(payload)  # type: ignore[arg-type]
        elif fc == "H":
            push("H", int(payload))  # type: ignore[arg-type]
        elif fc == "P":
            push("P", int(payload))  # type: ignore[arg-type]
        elif fc in ("q", "Q"):
            pass  # quality-only features; positions unaffected
        else:
            raise CramError(f"unhandled feature {fc!r}")
    tail = r.rl - rpos
    if tail > 0:
        _fill_match(bases, covered, rpos, tail, ref, ref_cursor)
        push("M", tail)
    if not comp.rr_required:
        # no-ref: uncovered positions drain the BA series in read order —
        # one batched series read, scattered by the coverage mask.
        n_unc = r.rl - sum(covered)
        if n_unc > 0:
            run = E("BA").read_byte_run(ctx, n_unc)
            if n_unc == r.rl:
                bases[:] = run
            else:
                dst = np.frombuffer(bases, dtype=np.uint8)
                idx = np.nonzero(
                    np.frombuffer(covered, dtype=np.uint8) == 0
                )[0]
                dst[idx] = np.frombuffer(run, dtype=np.uint8)
    return bases.decode("latin-1"), cigar_ops


def _upper(b: int) -> int:
    return b - 32 if 97 <= b <= 122 else b


_UPPER_TABLE = bytes(
    b - 32 if 97 <= b <= 122 else b for b in range(256)
)


def _fill_match(
    bases: bytearray,
    covered: bytearray,
    rpos: int,
    n: int,
    ref: Optional[bytes],
    ref_cursor: int,
) -> None:
    # Slice assignment on a bytearray silently resizes on length mismatch;
    # out-of-range cursors from corrupt features must ERROR, not shift
    # every downstream base (the old per-index loop raised IndexError).
    if rpos < 0 or rpos + n > len(covered):
        raise CramError(
            f"feature positions run past the read length "
            f"({rpos}+{n} > {len(covered)})"
        )
    if ref is None:
        return  # no-ref mode: BA fills later, covered stays 0
    if ref_cursor < 0:
        raise CramError(f"reference cursor negative ({ref_cursor})")
    avail = min(n, max(0, len(ref) - ref_cursor))
    if avail > 0:
        bases[rpos : rpos + avail] = ref[
            ref_cursor : ref_cursor + avail
        ].translate(_UPPER_TABLE)
    covered[rpos : rpos + n] = b"\x01" * n


# ---------------------------------------------------------------------------
# Container decode / whole-file read
# ---------------------------------------------------------------------------


def decode_container(
    data: bytes,
    ch: ContainerHeader,
    major: int,
    ref_getter: Optional[Callable[[int], bytes]] = None,
    *,
    stream=None,
    errors: str = "strict",
) -> List[BamRecord]:
    """All records of one data container.

    Two passes: the frame walk collects every block of the container
    still-compressed, then one ``decompress_batch`` call inflates them
    all through the codec seam — via ``stream`` (a
    :class:`~hadoop_bam_tpu.device_stream.DeviceStream`, whose policy
    may arm the rANS lockstep lanes) when given, the host batch
    otherwise.  ``errors="salvage"`` quarantines a slice whose blocks
    fail to inflate (``cram.slice.quarantined``) instead of killing the
    container; a salvaged-away compression header quarantines the whole
    container."""
    from . import cram_codecs
    from .cram_codecs import DecodeContext
    from ..utils.tracing import METRICS, span

    if ch.is_eof or ch.n_records == 0:
        return []
    pos = ch.offset + ch.header_size
    end = ch.offset + ch.header_size + ch.length
    frames: List[BlockFrame] = []
    while pos < end:
        fr, pos = Block.read_frame(data, pos, major)
        frames.append(fr)
    if not frames:
        return []
    triples = [(f.method, f.payload, f.raw_size) for f in frames]
    if stream is not None:
        raws = stream.decompress_cram_blocks(triples, errors=errors)
    else:
        raws = cram_codecs.decompress_batch(triples, errors=errors)

    def _block(i: int) -> Optional[Block]:
        if raws[i] is None:
            return None
        return Block.finish(frames[i], raws[i])

    comp_block = _block(0)
    if comp_block is None:
        METRICS.count("cram.container.quarantined", 1)
        return []
    if comp_block.content_type != CT_COMPRESSION_HEADER:
        raise CramError("expected compression-header block")
    comp = CompressionHeader.parse(comp_block.raw)
    out: List[BamRecord] = []
    i = 1
    with span("cram.stage.series", category="stage"):
        while i < len(frames):
            if frames[i].content_type != CT_SLICE_HEADER:
                raise CramError("expected slice-header block")
            sh_block = _block(i)
            n_blocks = (
                SliceHeader.parse(sh_block.raw, major).n_blocks
                if sh_block is not None
                else None
            )
            if n_blocks is None:
                # Slice header lost in salvage: its member count is
                # unknown, so the rest of the container is unwalkable.
                METRICS.count("cram.slice.quarantined", 1)
                break
            sh = SliceHeader.parse(sh_block.raw, major)
            first, i = i + 1, i + 1 + n_blocks
            members = [_block(j) for j in range(first, i)]
            if any(b is None for b in members):
                METRICS.count("cram.slice.quarantined", 1)
                continue
            core = b""
            external: Dict[int, bytes] = {}
            for blk in members:
                if blk.content_type == CT_CORE:
                    core = blk.raw
                elif blk.content_type == CT_EXTERNAL:
                    external[blk.content_id] = blk.raw
                else:
                    raise CramError(
                        f"unexpected block type {blk.content_type} in slice"
                    )
            rg = ref_getter
            if sh.embedded_ref_id >= 0 and sh.embedded_ref_id in external:
                # position the embedded block at the slice start, once
                padded = b"N" * (sh.start - 1) + external[sh.embedded_ref_id]

                def rg(_refid, _p=padded):  # noqa: ANN001
                    return _p

            ctx = DecodeContext(core, external)
            out.extend(_decode_slice_records(major, comp, sh, ctx, rg))
    return out


def read_cram_header_text(data: bytes) -> str:
    """SAM header text from the first (file-header) container."""
    major, _ = parse_file_definition(data)
    ch = parse_container_header(data, FILE_DEFINITION_LEN, major)
    blk, _ = Block.read(data, ch.offset + ch.header_size, major)
    if blk.content_type != CT_FILE_HEADER:
        raise CramError("first container is not the file header")
    (n,) = struct.unpack_from("<i", blk.raw, 0)
    return blk.raw[4 : 4 + n].decode()


def read_cram(
    path_or_bytes,
    ref_getter: Optional[Callable[[int], bytes]] = None,
    *,
    stream=None,
    errors: str = "strict",
):
    """(BamHeader, records) for a whole CRAM file."""
    data = (
        path_or_bytes
        if isinstance(path_or_bytes, (bytes, bytearray))
        else open(path_or_bytes, "rb").read()
    )
    from .bam import header_from_text

    major, _ = parse_file_definition(data)
    header = header_from_text(read_cram_header_text(data))
    out: List[BamRecord] = []
    for ch in iter_containers(data)[1:]:
        out.extend(
            decode_container(
                data, ch, major, ref_getter, stream=stream, errors=errors
            )
        )
    return header, out


# ---------------------------------------------------------------------------
# Writer (CRAM 3.0: external encodings, no-ref, detached mates)
# ---------------------------------------------------------------------------

# fixed external content ids per data series
_W_IDS = {
    "BF": 1, "CF": 2, "RI": 3, "RL": 4, "AP": 5, "RG": 6, "MF": 8,
    "NS": 9, "NP": 10, "TS": 11, "TL": 12, "FN": 13, "FC": 14, "FP": 15,
    "DL": 16, "BS": 17, "HC": 18, "PD": 19, "RS": 20, "BA": 21, "QS": 22,
    "MQ": 23,
}
_W_RN = 7  # byte-array-stop stream for names
_W_IN = 24  # insertion bases (stop)
_W_SC = 25  # soft-clip bases (stop)
_W_TAG_LEN = 26  # tag value lengths
_W_TAG_VAL = 27  # tag value bytes
_STOP = 0x00


class _StreamSet:
    def __init__(self):
        self.streams: Dict[int, bytearray] = {}

    def put_itf8(self, cid: int, v: int) -> None:
        self.streams.setdefault(cid, bytearray()).extend(write_itf8(v))

    def put_byte(self, cid: int, b: int) -> None:
        self.streams.setdefault(cid, bytearray()).append(b)

    def put_bytes(self, cid: int, b: bytes) -> None:
        self.streams.setdefault(cid, bytearray()).extend(b)


def _split_tags(tags_raw: bytes) -> List[Tuple[bytes, int, bytes]]:
    """BAM aux blob → [(2-byte tag, type byte, value bytes incl. any NUL)]."""
    out = []
    p = 0
    n = len(tags_raw)
    while p + 3 <= n:
        tag = tags_raw[p : p + 2]
        t = tags_raw[p + 2]
        p += 3
        c = chr(t)
        if c in "AcC":
            size = 1
        elif c in "sS":
            size = 2
        elif c in "iIf":
            size = 4
        elif c in "ZH":
            size = tags_raw.index(b"\x00", p) - p + 1
        elif c == "B":
            sub = chr(tags_raw[p])
            (cnt,) = struct.unpack_from("<I", tags_raw, p + 1)
            per = {"c": 1, "C": 1, "s": 2, "S": 2, "i": 4, "I": 4, "f": 4}[sub]
            size = 5 + cnt * per
        else:
            raise CramError(f"unknown aux type {c!r}")
        out.append((tag, t, tags_raw[p : p + size]))
        p += size
    return out


def _build_compression_header(
    td: List[List[Tuple[bytes, int]]], tag_keys: List[int]
) -> bytes:
    from .cram_codecs import (
        encoding_byte_array_len_external,
        encoding_byte_array_stop,
        encoding_external,
    )

    # preservation map: RN=1 AP=0 RR=0 SM TD
    pres = bytearray()
    entries = 0
    for key, val in (
        (b"RN", bytes([1])),
        (b"AP", bytes([0])),
        (b"RR", bytes([0])),
        (b"SM", _DEFAULT_SUB),
    ):
        pres += key + val
        entries += 1
    td_blob = (
        b"\x00".join(
            b"".join(tag + bytes([t]) for tag, t in line) for line in td
        )
        + b"\x00"
    )
    pres += b"TD" + write_itf8(len(td_blob)) + td_blob
    entries += 1
    pres_map = write_itf8(entries) + pres

    enc = bytearray()
    n_enc = 0
    for key, cid in _W_IDS.items():
        enc += key.encode() + encoding_external(cid)
        n_enc += 1
    enc += b"RN" + encoding_byte_array_stop(_STOP, _W_RN)
    enc += b"IN" + encoding_byte_array_stop(_STOP, _W_IN)
    enc += b"SC" + encoding_byte_array_stop(_STOP, _W_SC)
    n_enc += 3
    enc_map = write_itf8(n_enc) + enc

    tags = bytearray()
    for key in tag_keys:
        tags += write_itf8(key) + encoding_byte_array_len_external(
            _W_TAG_LEN, _W_TAG_VAL
        )
    tag_map = write_itf8(len(tag_keys)) + tags

    out = bytearray()
    out += write_itf8(len(pres_map)) + pres_map
    out += write_itf8(len(enc_map)) + enc_map
    out += write_itf8(len(tag_map)) + tag_map
    return bytes(out)


def encode_container(
    records: Sequence[BamRecord],
    record_counter: int,
    major: int = 3,
    codec: str = "gzip",
) -> bytes:
    """One container holding one multi-ref slice with the given records.

    CIGAR normalisations inherent to CRAM (identical to htslib/htsjdk):
    '='/'X' runs collapse to 'M' (the distinction is reference-derived, not
    stored), and flag-unmapped records store no features, so any CIGAR they
    carry reads back as '*'.

    ``codec`` picks the external-block compression: ``"gzip"`` (the
    default, htsjdk's stance) or ``"rans"`` (rANS 4x8 — the streams the
    lockstep-lane decoder eats, used by tests and the bench CRAM twin).
    """
    # tag dictionary
    td: List[List[Tuple[bytes, int]]] = []
    td_index: Dict[tuple, int] = {}
    rec_tl: List[int] = []
    rec_tags: List[List[Tuple[bytes, int, bytes]]] = []
    for rec in records:
        tags = _split_tags(rec.tags_raw)
        sig = tuple((bytes(t), ty) for t, ty, _ in tags)
        if sig not in td_index:
            td_index[sig] = len(td)
            td.append([(t, ty) for t, ty, _ in tags])
        rec_tl.append(td_index[sig])
        rec_tags.append(tags)
    tag_keys = sorted(
        {
            (t[0] << 16) | (t[1] << 8) | ty
            for line in td
            for t, ty in line
        }
    )

    s = _StreamSet()
    for rec, tl, tags in zip(records, rec_tl, rec_tags):
        flag = rec.flag
        cf = CF_QS_STORED | CF_DETACHED
        s.put_itf8(_W_IDS["BF"], flag)
        s.put_itf8(_W_IDS["CF"], cf)
        s.put_itf8(_W_IDS["RI"], rec.refid)
        l_seq = rec.l_seq
        s.put_itf8(_W_IDS["RL"], l_seq)
        s.put_itf8(_W_IDS["AP"], rec.pos + 1)
        s.put_itf8(_W_IDS["RG"], -1)
        s.put_bytes(_W_RN, rec.read_name.encode() + bytes([_STOP]))
        # detached mate data
        mf = 0
        if flag & FLAG_MATE_REVERSE:
            mf |= MF_MATE_NEG_STRAND
        if flag & FLAG_MATE_UNMAPPED:
            mf |= MF_MATE_UNMAPPED
        s.put_itf8(_W_IDS["MF"], mf)
        s.put_itf8(_W_IDS["NS"], rec.next_refid)
        s.put_itf8(_W_IDS["NP"], rec.next_pos + 1)
        s.put_itf8(_W_IDS["TS"], rec.tlen)
        s.put_itf8(_W_IDS["TL"], tl)
        for tag, ty, val in tags:
            s.put_itf8(_W_TAG_LEN, len(val))
            s.put_bytes(_W_TAG_VAL, val)
        seq = rec.seq
        seq_b = b"" if seq == "*" else seq.encode()
        if not (flag & FLAG_UNMAPPED):
            # features: non-M cigar ops; M bases go through BA (no-ref)
            features: List[Tuple[int, str, bytes, int]] = []
            rpos = 1
            for n, op in rec.cigar:
                if op in ("M", "=", "X"):
                    rpos += n
                elif op == "S":
                    features.append((rpos, "S", seq_b[rpos - 1 : rpos - 1 + n], 0))
                    rpos += n
                elif op == "I":
                    features.append((rpos, "I", seq_b[rpos - 1 : rpos - 1 + n], 0))
                    rpos += n
                elif op == "D":
                    features.append((rpos, "D", b"", n))
                elif op == "N":
                    features.append((rpos, "N", b"", n))
                elif op == "H":
                    features.append((rpos, "H", b"", n))
                elif op == "P":
                    features.append((rpos, "P", b"", n))
                else:
                    raise CramError(f"unsupported cigar op {op}")
            s.put_itf8(_W_IDS["FN"], len(features))
            prev = 0
            covered = bytearray(l_seq)
            for fpos, fc, payload, num in features:
                s.put_byte(_W_IDS["FC"], ord(fc))
                s.put_itf8(_W_IDS["FP"], fpos - prev)
                prev = fpos
                if fc == "S":
                    s.put_bytes(_W_SC, payload + bytes([_STOP]))
                    for k in range(len(payload)):
                        covered[fpos - 1 + k] = 1
                elif fc == "I":
                    s.put_bytes(_W_IN, payload + bytes([_STOP]))
                    for k in range(len(payload)):
                        covered[fpos - 1 + k] = 1
                elif fc == "D":
                    s.put_itf8(_W_IDS["DL"], num)
                elif fc == "N":
                    s.put_itf8(_W_IDS["RS"], num)
                elif fc == "H":
                    s.put_itf8(_W_IDS["HC"], num)
                elif fc == "P":
                    s.put_itf8(_W_IDS["PD"], num)
            s.put_itf8(_W_IDS["MQ"], rec.mapq)
            s.put_bytes(_W_IDS["QS"], rec.qual or b"\xff" * l_seq)
            # no-ref BA fill for uncovered positions
            for k in range(l_seq):
                if not covered[k]:
                    s.put_byte(_W_IDS["BA"], seq_b[k] if k < len(seq_b) else ord("N"))
        else:
            s.put_bytes(_W_IDS["BA"], seq_b.ljust(l_seq, b"N"))
            s.put_bytes(_W_IDS["QS"], rec.qual or b"\xff" * l_seq)

    mapped = [r for r in records if r.refid >= 0]
    if mapped:
        start = min(r.pos for r in mapped) + 1
        end = max(r.pos + max(r.reference_length(), 1) for r in mapped)
        span = max(end - start + 1, 0)
    else:
        start, span = 0, 0
    n_ext = len(s.streams)
    sh = SliceHeader(
        ref_seq_id=-2,
        start=start if len({r.refid for r in records}) == 1 else 0,
        span=span if len({r.refid for r in records}) == 1 else 0,
        n_records=len(records),
        record_counter=record_counter,
        n_blocks=1 + n_ext,
        content_ids=sorted(s.streams),
        embedded_ref_id=-1,
        md5=b"\x00" * 16,
    )
    from .cram_codecs import METHOD_GZIP, METHOD_RANS, METHOD_RAW

    ext_method = METHOD_RANS if codec == "rans" else METHOD_GZIP
    blocks = bytearray()
    comp_raw = _build_compression_header(td, tag_keys)
    blocks += Block(METHOD_RAW, CT_COMPRESSION_HEADER, 0, comp_raw).write(
        major, METHOD_GZIP
    )
    landmark = len(blocks)
    slice_blocks = bytearray()
    slice_blocks += Block(
        METHOD_RAW, CT_SLICE_HEADER, 0, sh.encode(major)
    ).write(major, METHOD_RAW)
    slice_blocks += Block(METHOD_RAW, CT_CORE, 0, b"").write(
        major, METHOD_RAW
    )
    for cid in sorted(s.streams):
        slice_blocks += Block(
            METHOD_RAW, CT_EXTERNAL, cid, bytes(s.streams[cid])
        ).write(major, ext_method)
    blocks += slice_blocks

    hdr = bytearray()
    hdr += struct.pack("<i", len(blocks))
    hdr += write_itf8(-2)
    hdr += write_itf8(sh.start)
    hdr += write_itf8(sh.span)
    hdr += write_itf8(len(records))
    hdr += (write_ltf8 if major >= 3 else write_itf8)(record_counter)
    hdr += (write_ltf8 if major >= 3 else write_itf8)(
        sum(r.l_seq for r in records)
    )
    hdr += write_itf8(3 + n_ext)  # comp hdr + slice hdr + core + externals
    hdr += write_itf8(1)
    hdr += write_itf8(landmark)
    if major >= 3:
        hdr += struct.pack("<I", zlib.crc32(bytes(hdr)))
    return bytes(hdr) + bytes(blocks)


def encode_file_header_container(text: str, major: int = 3) -> bytes:
    raw = struct.pack("<i", len(text.encode())) + text.encode()
    from .cram_codecs import METHOD_RAW

    blk = Block(METHOD_RAW, CT_FILE_HEADER, 0, raw).write(major, METHOD_RAW)
    hdr = bytearray()
    hdr += struct.pack("<i", len(blk))
    hdr += write_itf8(0)
    hdr += write_itf8(0)
    hdr += write_itf8(0)
    hdr += write_itf8(0)
    hdr += (write_ltf8 if major >= 3 else write_itf8)(0)
    hdr += (write_ltf8 if major >= 3 else write_itf8)(0)
    hdr += write_itf8(1)
    hdr += write_itf8(0)
    if major >= 3:
        hdr += struct.pack("<I", zlib.crc32(bytes(hdr)))
    return bytes(hdr) + blk


def write_cram(
    stream,
    header,
    records: Sequence[BamRecord],
    records_per_container: int = 10000,
    append_eof: bool = True,
    codec: str = "gzip",
) -> None:
    """Complete CRAM 3.0 file: file definition, header container, data
    containers, EOF marker (suppressible for headerless parts, the
    CRAMRecordWriter.java:98-101 semantics).  ``codec="rans"`` writes
    the external series rANS-coded (see :func:`encode_container`)."""
    stream.write(MAGIC + bytes([3, 0]) + b"\x00" * 20)
    stream.write(encode_file_header_container(header.text, 3))
    counter = 0
    for i in range(0, len(records), records_per_container):
        chunk = records[i : i + records_per_container]
        stream.write(encode_container(chunk, counter, 3, codec=codec))
        counter += len(chunk)
    if append_eof:
        stream.write(EOF_V3)
