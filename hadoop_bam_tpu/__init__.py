"""hadoop_bam_tpu — a TPU-native framework with the capabilities of Hadoop-BAM.

Distributed, record-parallel reading/writing/sorting of bioinformatics file
formats (BAM/SAM/CRAM, VCF/BCF, FASTQ/FASTA/QSEQ), re-designed TPU-first:

- host-side Python owns file-format intelligence (headers, indices,
  record-aligned split planning, interval-bounded traversal, part merging),
- a C++ host library owns the irregular hot host path (batched BGZF inflate,
  BAM record scanning),
- JAX/XLA/Pallas own the dense phases: batched record-field decode into
  structure-of-arrays tensors, 64-bit coordinate keying, per-chip sort, and a
  cross-chip all-to-all range-partitioned shuffle over a `jax.sharding.Mesh`
  (the MapReduce-shuffle equivalent; key semantics preserved from
  reference BAMRecordReader.java:81-121).

The reference architecture being matched is huangzhibo/Hadoop-BAM (pure Java on
Hadoop MapReduce); see SURVEY.md at the repo root for the capability map.
"""

__version__ = "0.1.0"

from .conf import Configuration  # noqa: F401
