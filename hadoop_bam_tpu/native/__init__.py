"""ctypes bindings for the native host library (build-on-first-import).

The shared library compiles from ``bgzf_native.cpp`` with g++ -O3 -lz the
first time it's needed (no pybind11 in the image; plain C ABI + ctypes).  All
entry points have pure-Python fallbacks in spec/ — ``available()`` reports
whether the fast path loaded, and callers may pass ``native=False`` to force
the oracle path (used by tests to cross-validate the two).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import tempfile
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "bgzf_native.cpp")
_LIB_NAME = "_libhbam_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed: Optional[str] = None

MAX_BLOCK = 0x10000
_ABI = 6


def _build(lib_path: str) -> None:
    with tempfile.TemporaryDirectory(dir=_HERE) as td:
        tmp = os.path.join(td, _LIB_NAME)
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
            _SRC, "-o", tmp, "-lz",
        ]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, lib_path)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64, i32, u8p = ctypes.c_int64, ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8)
    i64p, i32p = ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32)
    lib.hbam_abi_version.restype = ctypes.c_int
    lib.hbam_scan_blocks.restype = i64
    lib.hbam_scan_blocks.argtypes = [u8p, i64, i64, i64p, i32p, i32p, i64]
    lib.hbam_find_next_block.restype = i64
    lib.hbam_find_next_block.argtypes = [u8p, i64, i64, i64]
    lib.hbam_inflate_blocks.restype = i64
    lib.hbam_inflate_blocks.argtypes = [
        u8p, i64p, i32p, i64, u8p, i64p, i32p, ctypes.c_int, ctypes.c_int,
    ]
    lib.hbam_deflate_blocks.restype = i64
    lib.hbam_deflate_blocks.argtypes = [
        u8p, i64p, i64, ctypes.c_int, u8p, i32p, ctypes.c_int,
    ]
    lib.hbam_record_chain.restype = i64
    lib.hbam_record_chain.argtypes = [u8p, i64, i64, i64p, i64]
    lib.hbam_record_chain_partial.restype = i64
    lib.hbam_record_chain_partial.argtypes = [u8p, i64, i64, i64p, i64, i64p]
    lib.hbam_gather_records.restype = i64
    lib.hbam_gather_records.argtypes = [u8p, i64p, i64p, i64p, i64, u8p]
    lib.hbam_gather_records_chunked.restype = i64
    lib.hbam_gather_records_chunked.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), i32p, i64p, i64p, i64p, i64, u8p,
    ]
    lib.hbam_gather_rows.restype = None
    lib.hbam_gather_rows.argtypes = [u8p, i64p, i64p, i64, i64, u8p, ctypes.c_int]
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.hbam_parse_i64.restype = i64
    lib.hbam_parse_i64.argtypes = [u8p, i64p, i64p, i64, i64p, ctypes.c_int]
    lib.hbam_parse_cigars.restype = i64
    lib.hbam_parse_cigars.argtypes = [
        u8p, i64p, i64p, i64, i64p, i64p, i64p, u32p, ctypes.c_int,
    ]
    lib.hbam_encode_tags.restype = i64
    lib.hbam_encode_tags.argtypes = [
        u8p, i64p, i64p, i64, i64p, i64p, u8p, ctypes.c_int,
    ]
    lib.hbam_count_byte.restype = i64
    lib.hbam_count_byte.argtypes = [u8p, i64, i64, ctypes.c_int]
    lib.hbam_bcf_scan.restype = i64
    lib.hbam_bcf_scan.argtypes = [
        u8p, i64, i64, i64, i64, i64, i64, i64p, i64p, i64p, i64,
    ]
    lib.hbam_sam_scan.restype = i64
    lib.hbam_sam_scan.argtypes = (
        [u8p, i64, i64, i64, i64, i64p] + [i64p] * 16 + [i64, i64]
    )
    lib.hbam_sam_emit.restype = i64
    lib.hbam_sam_emit.argtypes = (
        [u8p, i64, i64p, i64p]
        + [i32p] * 10
        + [i64p, i64p, i64p, u32p, i64p, u8p, i64p, i64p, u8p,
           i64p, i64p, u8p, u8p, ctypes.c_int]
    )
    return lib


def _get() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed is not None:
        return _lib
    with _lock:
        if _lib is not None or _load_failed is not None:
            return _lib
        lib_path = os.path.join(_HERE, _LIB_NAME)
        try:
            if not os.path.exists(lib_path) or os.path.getmtime(
                lib_path
            ) < os.path.getmtime(_SRC):
                _build(lib_path)
            try:
                lib = _bind(ctypes.CDLL(lib_path))
                stale = lib.hbam_abi_version() != _ABI
            except (AttributeError, OSError):
                stale = True  # older .so missing symbols → rebuild
            if stale:
                _build(lib_path)
                lib = _bind(ctypes.CDLL(lib_path))
            _lib = lib
        except Exception as e:  # missing toolchain → oracle fallback
            _load_failed = str(e)
    return _lib


def available() -> bool:
    return _get() is not None


def load_error() -> Optional[str]:
    _get()
    return _load_failed


def _as_u8(data) -> np.ndarray:
    if isinstance(data, np.ndarray):
        return np.ascontiguousarray(data, dtype=np.uint8)
    return np.frombuffer(data, dtype=np.uint8)


def _ptr(a: np.ndarray, typ):
    return a.ctypes.data_as(ctypes.POINTER(typ))


def default_threads() -> int:
    return max(1, (os.cpu_count() or 1))


def scan_blocks(data) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(coffsets i64, csizes i32, usizes i32) of the back-to-back chain."""
    a = _as_u8(data)
    lib = _get()
    if lib is None:
        from ..spec import bgzf

        blocks = bgzf.scan_blocks(bytes(a))
        return (
            np.array([b.coffset for b in blocks], dtype=np.int64),
            np.array([b.csize for b in blocks], dtype=np.int32),
            np.array([b.usize for b in blocks], dtype=np.int32),
        )
    cap = max(16, len(a) // 64 + 2)  # min BGZF block is ~30 bytes; generous
    while True:
        co = np.empty(cap, dtype=np.int64)
        cs = np.empty(cap, dtype=np.int32)
        us = np.empty(cap, dtype=np.int32)
        n = lib.hbam_scan_blocks(
            _ptr(a, ctypes.c_uint8), len(a), 0,
            _ptr(co, ctypes.c_int64), _ptr(cs, ctypes.c_int32),
            _ptr(us, ctypes.c_int32), cap,
        )
        if n == -2:
            cap *= 2
            continue
        if n < 0:
            from ..spec.bgzf import BgzfError

            raise BgzfError("bad BGZF chain")
        return co[:n].copy(), cs[:n].copy(), us[:n].copy()


def find_next_block(data, start: int, end: Optional[int] = None) -> int:
    """Next plausible block-header offset at/after start, or -1."""
    a = _as_u8(data)
    end = len(a) if end is None else end
    lib = _get()
    if lib is None:
        from ..spec import bgzf

        found = bgzf.find_next_block(bytes(a), start)
        return -1 if found is None or found[0] >= end else found[0]
    return lib.hbam_find_next_block(_ptr(a, ctypes.c_uint8), len(a), start, end)


def inflate_blocks(
    data,
    coffsets: np.ndarray,
    csizes: np.ndarray,
    usizes: np.ndarray,
    check_crc: bool = True,
    threads: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Batched inflate → (payload bytes concatenated, block start offsets).

    Returns ``(out, out_offsets)`` where block i's payload is
    ``out[out_offsets[i]:out_offsets[i+1]]`` (out_offsets has n+1 entries).
    """
    a = _as_u8(data)
    n = len(coffsets)
    out_offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(usizes.astype(np.int64), out=out_offsets[1:])
    total = int(out_offsets[-1])
    out = np.empty(total, dtype=np.uint8)
    lib = _get()
    if lib is None:
        from ..spec import bgzf

        raw = bytes(a)
        for i in range(n):
            payload, _ = bgzf.inflate_block(raw, int(coffsets[i]), check_crc)
            out[int(out_offsets[i]) : int(out_offsets[i + 1])] = np.frombuffer(
                payload, dtype=np.uint8
            )
        return out, out_offsets
    co = np.ascontiguousarray(coffsets, dtype=np.int64)
    cs = np.ascontiguousarray(csizes, dtype=np.int32)
    sizes = np.zeros(n, dtype=np.int32)
    err = lib.hbam_inflate_blocks(
        _ptr(a, ctypes.c_uint8), _ptr(co, ctypes.c_int64),
        _ptr(cs, ctypes.c_int32), n, _ptr(out, ctypes.c_uint8),
        _ptr(out_offsets, ctypes.c_int64), _ptr(sizes, ctypes.c_int32),
        1 if check_crc else 0, threads or default_threads(),
    )
    if err != 0:
        from ..spec.bgzf import BgzfError

        raise BgzfError(f"inflate failed in block {err - 1}")
    return out, out_offsets


def deflate_blocks(
    payload,
    level: int = 6,
    threads: Optional[int] = None,
    block_payload: int = 0xFF00,
) -> bytes:
    """Batched BGZF compression of a byte stream (no terminator appended)."""
    a = _as_u8(payload)
    n = max(1, (len(a) + block_payload - 1) // block_payload) if len(a) else 0
    if n == 0:
        return b""
    in_offsets = np.arange(n + 1, dtype=np.int64) * block_payload
    in_offsets[-1] = len(a)
    lib = _get()
    if lib is None:
        from ..spec import bgzf

        raw = bytes(a)
        return b"".join(
            bgzf.compress_block(
                raw[int(in_offsets[i]) : int(in_offsets[i + 1])], level
            )
            for i in range(n)
        )
    out = np.empty(n * MAX_BLOCK, dtype=np.uint8)
    sizes = np.zeros(n, dtype=np.int32)
    err = lib.hbam_deflate_blocks(
        _ptr(a, ctypes.c_uint8), _ptr(in_offsets, ctypes.c_int64), n, level,
        _ptr(out, ctypes.c_uint8), _ptr(sizes, ctypes.c_int32),
        threads or default_threads(),
    )
    if err != 0:
        from ..spec.bgzf import BgzfError

        raise BgzfError(f"deflate failed in block {err - 1}")
    parts = [
        out[i * MAX_BLOCK : i * MAX_BLOCK + int(sizes[i])].tobytes()
        for i in range(n)
    ]
    return b"".join(parts)


def record_chain(data, start: int, end: Optional[int] = None) -> np.ndarray:
    """BAM record-boundary offsets over an uncompressed stream."""
    a = _as_u8(data)
    end = len(a) if end is None else end
    lib = _get()
    if lib is None:
        from ..spec import bam

        return bam.record_offsets(a, start, end)
    cap = max(16, (end - start) // 36 + 2)  # min record body is ~32+4 bytes
    while True:
        offs = np.empty(cap, dtype=np.int64)
        n = lib.hbam_record_chain(
            _ptr(a, ctypes.c_uint8), start, end, _ptr(offs, ctypes.c_int64), cap
        )
        if n == -2:
            cap *= 2
            continue
        if n < 0:
            from ..spec.bam import BamError

            raise BamError(f"record chain misaligned in [{start},{end})")
        return offs[:n].copy()


def record_chain_partial(
    data, start: int, end: Optional[int] = None
) -> Tuple[np.ndarray, int]:
    """Record-boundary offsets over ``[start, end)`` plus the resume point.

    Unlike :func:`record_chain` a truncated tail record is not an error:
    the walk stops before it and ``resume`` is where it (or the next
    record) starts, so callers can inflate spill blocks and continue."""
    a = _as_u8(data)
    end = len(a) if end is None else end
    lib = _get()
    if lib is None:
        offs = []
        pos = start
        while pos + 4 <= end:
            (bs,) = struct.unpack_from("<I", a, pos)
            if pos + 4 + bs > end:
                break
            offs.append(pos)
            pos += 4 + bs
        return np.asarray(offs, dtype=np.int64), pos
    cap = max(16, (end - start) // 36 + 2)
    resume = np.zeros(1, dtype=np.int64)
    while True:
        offs = np.empty(cap, dtype=np.int64)
        n = lib.hbam_record_chain_partial(
            _ptr(a, ctypes.c_uint8), start, end,
            _ptr(offs, ctypes.c_int64), cap, _ptr(resume, ctypes.c_int64),
        )
        if n == -2:
            cap *= 2
            continue
        return offs[:n].copy(), int(resume[0])


def gather_records(
    data,
    rec_off: np.ndarray,
    rec_len: np.ndarray,
    order: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Permuted concat of (block_size word + body) per record — one memcpy
    each, no index-array temporaries (fast on low-core hosts)."""
    a = _as_u8(data)
    lib = _get()
    off = np.ascontiguousarray(rec_off, dtype=np.int64)
    ln = np.ascontiguousarray(rec_len, dtype=np.int64)
    if len(off) and (
        (off.min() < 4)
        or int((off + ln).max()) > len(a)
        or ln.min() < 0
    ):
        raise IndexError("record extents out of bounds for data buffer")
    if order is not None:
        order = np.ascontiguousarray(order, dtype=np.int64)
        if len(order) and (order.min() < 0 or order.max() >= len(off)):
            raise IndexError("order indices out of range")
        n = len(order)  # rows to emit — may be a slice of the batch
        total = int((ln[order] + 4).sum())
    else:
        n = len(off)
        total = int((ln + 4).sum())
    out = np.empty(total, dtype=np.uint8)
    if lib is None:
        w = 0
        idx = order if order is not None else np.arange(n)
        for r in idx:
            l = int(ln[r]) + 4
            s = int(off[r]) - 4
            out[w : w + l] = a[s : s + l]
            w += l
        return out
    lib.hbam_gather_records(
        _ptr(a, ctypes.c_uint8), _ptr(off, ctypes.c_int64),
        _ptr(ln, ctypes.c_int64),
        _ptr(order, ctypes.c_int64) if order is not None else None,
        n, _ptr(out, ctypes.c_uint8),
    )
    return out


def gather_records_chunked(
    chunks,
    chunk_id: np.ndarray,
    rec_off: np.ndarray,
    rec_len: np.ndarray,
    order: Optional[np.ndarray] = None,
    check: bool = True,
) -> np.ndarray:
    """Permuted concat of records scattered across several byte buffers.

    ``chunks`` is a sequence of uint8 arrays (one per file split);
    ``chunk_id[r]``/``rec_off[r]`` address record ``r``'s body inside its
    chunk.  Equivalent to :func:`gather_records` over the concatenation of
    the chunks — without ever building that concatenation.

    ``check=False`` skips the O(n) extent validation — callers that gather
    the same batch repeatedly (one call per output part) validate once and
    reuse (the bounds feed raw memcpys, so unvalidated extents must come
    from a trusted decode)."""
    arrs = [_as_u8(c) for c in chunks]
    cid = np.ascontiguousarray(chunk_id, dtype=np.int32)
    off = np.ascontiguousarray(rec_off, dtype=np.int64)
    ln = np.ascontiguousarray(rec_len, dtype=np.int64)
    if check and len(off):
        if cid.min() < 0 or cid.max() >= len(arrs):
            raise IndexError("chunk_id out of range")
        if off.min() < 4 or ln.min() < 0:
            raise IndexError("record extents out of bounds")
        sizes = np.asarray([len(a) for a in arrs], dtype=np.int64)
        if np.any(off + ln > sizes[cid]):
            raise IndexError("record extents out of bounds for chunk")
    if order is not None:
        order = np.ascontiguousarray(order, dtype=np.int64)
        if len(order) and (order.min() < 0 or order.max() >= len(off)):
            raise IndexError("order indices out of range")
        n = len(order)
        total = int((ln[order] + 4).sum())
    else:
        n = len(off)
        total = int((ln + 4).sum())
    out = np.empty(total, dtype=np.uint8)
    lib = _get()
    if lib is None:
        w = 0
        idx = order if order is not None else np.arange(n)
        for r in idx:
            l = int(ln[r]) + 4
            s = int(off[r]) - 4
            a = arrs[int(cid[r])]
            out[w : w + l] = a[s : s + l]
            w += l
        return out
    ptrs = (ctypes.c_void_p * len(arrs))(
        *[a.ctypes.data for a in arrs]
    )
    lib.hbam_gather_records_chunked(
        ptrs, _ptr(cid, ctypes.c_int32), _ptr(off, ctypes.c_int64),
        _ptr(ln, ctypes.c_int64),
        _ptr(order, ctypes.c_int64) if order is not None else None,
        n, _ptr(out, ctypes.c_uint8),
    )
    return out


def decompress_all(data, check_crc: bool = True, threads: Optional[int] = None) -> np.ndarray:
    """Whole-file batched BGZF decompress → uint8 array."""
    co, cs, us = scan_blocks(data)
    out, _ = inflate_blocks(data, co, cs, us, check_crc=check_crc, threads=threads)
    return out


def gather_rows(
    data,
    starts: np.ndarray,
    lens: np.ndarray,
    width: int,
    threads: Optional[int] = None,
) -> Optional[np.ndarray]:
    """Ragged byte rows → 0-padded uint8[n, width] matrix (threaded memcpy).

    Returns None when the native library is unavailable (callers fall back
    to the NumPy gather)."""
    lib = _get()
    if lib is None:
        return None
    a = _as_u8(data)
    st = np.ascontiguousarray(starts, dtype=np.int64)
    ln = np.ascontiguousarray(lens, dtype=np.int64)
    n = len(st)
    if n and (
        st.min() < 0
        or ln.min() < 0
        or int((st + np.minimum(ln, width)).max()) > len(a)
    ):
        raise IndexError("row extents out of bounds for data buffer")
    out = np.empty((n, width), dtype=np.uint8)
    if n == 0 or width == 0:
        return out
    lib.hbam_gather_rows(
        _ptr(a, ctypes.c_uint8), _ptr(st, ctypes.c_int64),
        _ptr(ln, ctypes.c_int64), n, width, _ptr(out, ctypes.c_uint8),
        threads or default_threads(),
    )
    return out


def parse_i64(data, starts, lens, threads: Optional[int] = None):
    """Vectorized decimal parse of byte slices → int64[n], or None when
    native is unavailable; raises ValueError when any slice is not a plain
    (optionally negative) decimal — callers fall back to the exact parser."""
    lib = _get()
    if lib is None:
        return None
    a = _as_u8(data)
    st = np.ascontiguousarray(starts, dtype=np.int64)
    ln = np.ascontiguousarray(lens, dtype=np.int64)
    out = np.empty(len(st), dtype=np.int64)
    if len(st) == 0:
        return out
    if st.min() < 0 or ln.min() < 0 or int((st + ln).max()) > len(a):
        raise IndexError("slice extents out of bounds")
    rc = lib.hbam_parse_i64(
        _ptr(a, ctypes.c_uint8), _ptr(st, ctypes.c_int64),
        _ptr(ln, ctypes.c_int64), len(st), _ptr(out, ctypes.c_int64),
        threads or default_threads(),
    )
    if rc != 0:
        raise ValueError("non-decimal field")
    return out


def parse_cigars(data, starts, lens, threads: Optional[int] = None):
    """All CIGAR fields → (n_ops i64[n], opvals u32 concat, span i64[n], op_off),
    or None when native is unavailable; ValueError on any malformed field."""
    lib = _get()
    if lib is None:
        return None
    a = _as_u8(data)
    st = np.ascontiguousarray(starts, dtype=np.int64)
    ln = np.ascontiguousarray(lens, dtype=np.int64)
    n = len(st)
    n_ops = np.zeros(n, dtype=np.int64)
    span = np.zeros(n, dtype=np.int64)
    if n == 0:
        return n_ops, np.empty(0, np.uint32), span, np.zeros(1, np.int64)
    if st.min() < 0 or ln.min() < 0 or int((st + ln).max()) > len(a):
        raise IndexError("slice extents out of bounds")
    thr = threads or default_threads()
    rc = lib.hbam_parse_cigars(
        _ptr(a, ctypes.c_uint8), _ptr(st, ctypes.c_int64),
        _ptr(ln, ctypes.c_int64), n, _ptr(n_ops, ctypes.c_int64),
        _ptr(span, ctypes.c_int64), None, None, thr,
    )
    if rc != 0:
        raise ValueError("malformed CIGAR")
    op_off = np.concatenate(([0], np.cumsum(n_ops)))
    opvals = np.empty(int(op_off[-1]), dtype=np.uint32)
    if len(opvals):
        rc = lib.hbam_parse_cigars(
            _ptr(a, ctypes.c_uint8), _ptr(st, ctypes.c_int64),
            _ptr(ln, ctypes.c_int64), n, _ptr(n_ops, ctypes.c_int64),
            _ptr(span, ctypes.c_int64), _ptr(op_off, ctypes.c_int64),
            _ptr(opvals, ctypes.c_uint32), thr,
        )
        if rc != 0:
            raise ValueError("malformed CIGAR")
    return n_ops, opvals, span, op_off


def sam_emit(
    text, rec_off, body_len, cols, name_src, name_len, op_off, opvals,
    seq_src, seq_star, qual_src, qual_len, qual_star, tag_off, tag_len,
    tag_blob, total: int, threads: Optional[int] = None,
):
    """Assemble all binary SAM records in one threaded native pass.

    ``cols`` = (refid, pos0, mapq, bin, n_ops, flag, l_seq, nrefid, npos0,
    tlen) int32 arrays.  Returns the uint8 blob, or None when native is
    unavailable; ValueError on a QUAL byte below '!'."""
    lib = _get()
    if lib is None:
        return None
    a = _as_u8(text)
    out = np.empty(total, dtype=np.uint8)  # C writes every byte
    n = len(rec_off)
    if n == 0:
        return out
    i64c = lambda x: np.ascontiguousarray(x, dtype=np.int64)
    i32c = lambda x: np.ascontiguousarray(x, dtype=np.int32)
    u8c = lambda x: np.ascontiguousarray(x, dtype=np.uint8)
    cols32 = [i32c(c) for c in cols]
    ov = np.ascontiguousarray(opvals, dtype=np.uint32)
    args = (
        [_ptr(a, ctypes.c_uint8), n,
         _ptr(i64c(rec_off), ctypes.c_int64),
         _ptr(i64c(body_len), ctypes.c_int64)]
        + [_ptr(c, ctypes.c_int32) for c in cols32]
        + [
            _ptr(i64c(name_src), ctypes.c_int64),
            _ptr(i64c(name_len), ctypes.c_int64),
            _ptr(i64c(op_off), ctypes.c_int64),
            _ptr(ov, ctypes.c_uint32),
            _ptr(i64c(seq_src), ctypes.c_int64),
            _ptr(u8c(seq_star), ctypes.c_uint8),
            _ptr(i64c(qual_src), ctypes.c_int64),
            _ptr(i64c(qual_len), ctypes.c_int64),
            _ptr(u8c(qual_star), ctypes.c_uint8),
            _ptr(i64c(tag_off), ctypes.c_int64),
            _ptr(i64c(tag_len), ctypes.c_int64),
            _ptr(u8c(tag_blob), ctypes.c_uint8),
            _ptr(out, ctypes.c_uint8),
            threads or default_threads(),
        ]
    )
    rc = lib.hbam_sam_emit(*args)
    if rc != 0:
        raise ValueError("QUAL byte below '!'")
    return out


def encode_tags(text, starts, lens, threads: Optional[int] = None):
    """SAM tag tokens → (enc_len i64[n], blob u8), or None when native is
    unavailable; ValueError when any token needs the exact encoder."""
    lib = _get()
    if lib is None:
        return None
    a = _as_u8(text)
    st = np.ascontiguousarray(starts, dtype=np.int64)
    ln = np.ascontiguousarray(lens, dtype=np.int64)
    n = len(st)
    enc_len = np.zeros(n, dtype=np.int64)
    if n == 0:
        return enc_len, np.empty(0, np.uint8)
    if st.min() < 0 or ln.min() < 0 or int((st + ln).max()) > len(a):
        raise IndexError("token extents out of bounds")
    thr = threads or default_threads()
    rc = lib.hbam_encode_tags(
        _ptr(a, ctypes.c_uint8), _ptr(st, ctypes.c_int64),
        _ptr(ln, ctypes.c_int64), n, _ptr(enc_len, ctypes.c_int64),
        None, None, thr,
    )
    if rc != 0:
        raise ValueError("tag token needs exact encoder")
    dst = np.concatenate(([0], np.cumsum(enc_len)))
    blob = np.empty(int(dst[-1]), dtype=np.uint8)
    rc = lib.hbam_encode_tags(
        _ptr(a, ctypes.c_uint8), _ptr(st, ctypes.c_int64),
        _ptr(ln, ctypes.c_int64), n, _ptr(enc_len, ctypes.c_int64),
        _ptr(dst, ctypes.c_int64), _ptr(blob, ctypes.c_uint8), thr,
    )
    if rc != 0:
        raise ValueError("tag token needs exact encoder")
    return enc_len, blob


def sam_scan(text, lo: int, hi: int, window_end: int):
    """One native pass over a SAM split: line table + 11-field table +
    core integers + tag-token table.  Returns a dict of arrays, None when
    native is unavailable, or ValueError when any line needs the exact
    parser."""
    lib = _get()
    if lib is None:
        return None
    a = _as_u8(text)
    nl_bound = (
        lib.hbam_count_byte(_ptr(a, ctypes.c_uint8), lo, min(hi, window_end), 0x0A)
        + 1
    )
    tab_bound = lib.hbam_count_byte(
        _ptr(a, ctypes.c_uint8), lo, window_end, 0x09
    ) + 1
    counts = np.zeros(2, dtype=np.int64)
    ints = np.empty(5 * nl_bound, dtype=np.int64)
    cols = {
        k: np.empty(nl_bound, dtype=np.int64)
        for k in (
            "name_src", "name_len", "rname_src", "rname_len", "cigar_src",
            "cigar_len", "rnext_src", "rnext_len", "seq_src", "seq_len",
            "qual_src", "qual_len",
        )
    }
    tok_start = np.empty(tab_bound, dtype=np.int64)
    tok_len = np.empty(tab_bound, dtype=np.int64)
    tok_rid = np.empty(tab_bound, dtype=np.int64)
    rc = lib.hbam_sam_scan(
        _ptr(a, ctypes.c_uint8), len(a), lo, hi, window_end,
        _ptr(counts, ctypes.c_int64), _ptr(ints, ctypes.c_int64),
        *(_ptr(cols[k], ctypes.c_int64) for k in (
            "name_src", "name_len", "rname_src", "rname_len", "cigar_src",
            "cigar_len", "rnext_src", "rnext_len", "seq_src", "seq_len",
            "qual_src", "qual_len",
        )),
        _ptr(tok_start, ctypes.c_int64), _ptr(tok_len, ctypes.c_int64),
        _ptr(tok_rid, ctypes.c_int64), nl_bound, tab_bound,
    )
    if rc != 0:
        raise ValueError("SAM line needs exact parser")
    n, T = int(counts[0]), int(counts[1])
    out = {k: v[:n] for k, v in cols.items()}
    out["ints"] = ints[: 5 * n].reshape(n, 5)
    out["tok_start"] = tok_start[:T]
    out["tok_len"] = tok_len[:T]
    out["tok_rid"] = tok_rid[:T]
    return out


def bcf_scan(data, start: int, end: int, n_contigs: int, n_strings: int,
             end_key: int):
    """BCF chain walk + full shared-block validation in one C pass.

    Returns (offsets i64[n], ref_len i64[n], end_info i64[n] with
    INT64_MIN for absent INFO/END), None when native is unavailable, or
    ValueError when any record needs the exact decoder (truncation, bad
    typed values, out-of-range dictionary indexes, ambiguous END)."""
    lib = _get()
    if lib is None:
        return None
    a = _as_u8(data)
    # A record is >= 32 bytes (8-byte lengths + 24 fixed shared).
    cap = max(16, (end - start) // 32 + 2)
    offs = np.empty(cap, dtype=np.int64)
    ref_len = np.empty(cap, dtype=np.int64)
    end_info = np.empty(cap, dtype=np.int64)
    n = lib.hbam_bcf_scan(
        _ptr(a, ctypes.c_uint8), len(a), start, end,
        n_contigs, n_strings, end_key,
        _ptr(offs, ctypes.c_int64), _ptr(ref_len, ctypes.c_int64),
        _ptr(end_info, ctypes.c_int64), cap,
    )
    if n == -1:
        raise ValueError("BCF record needs exact decoder")
    if n == -2:
        raise ValueError("BCF chain capacity exceeded")
    return offs[:n].copy(), ref_len[:n].copy(), end_info[:n].copy()
