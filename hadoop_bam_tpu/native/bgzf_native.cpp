// Native host path: batched BGZF block codec + BAM record chain walking.
//
// The reference delegates its hot host work to htsjdk's native zlib
// (BlockCompressedInputStream / BAMRecordCodec below reference L0).  This
// library is the TPU build's equivalent: block-granular batched
// inflate/deflate with an internal thread pool, BGZF header scanning with the
// split-guesser's candidate rules (BaseSplitGuesser.java:31-108 semantics),
// and the serial BAM record-boundary walk (the part that cannot be
// vectorized until offsets are known; SURVEY.md §7 stage 4).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).

#include <atomic>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

constexpr int64_t kHeaderFixed = 12;  // gzip header incl. XLEN
constexpr int64_t kFooter = 8;        // CRC32 + ISIZE
constexpr int64_t kMaxBlock = 0x10000;

inline uint16_t u16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0]) | (static_cast<uint16_t>(p[1]) << 8);
}
inline uint32_t u32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

// Parse a BGZF block header at data[pos]; returns total block size (bsize) or
// -1.  Mirrors the subfield walk incl. the exact-XLEN-landing cancellation
// (BaseSplitGuesser.java:80-90).
int64_t parse_header(const uint8_t* data, int64_t len, int64_t pos) {
  if (pos + kHeaderFixed > len) return -1;
  const uint8_t* p = data + pos;
  if (p[0] != 0x1f || p[1] != 0x8b || p[2] != 0x08 || p[3] != 0x04) return -1;
  const int64_t xlen = u16(p + 10);
  if (pos + kHeaderFixed + xlen > len) return -1;
  int64_t sub = kHeaderFixed;
  const int64_t end = kHeaderFixed + xlen;
  while (sub + 4 <= end) {
    const uint16_t slen = u16(p + sub + 2);
    if (p[sub] == 'B' && p[sub + 1] == 'C' && slen == 2) {
      if (sub + 6 > end) return -1;
      const int64_t bsize = static_cast<int64_t>(u16(p + sub + 4)) + 1;
      if (bsize < kHeaderFixed + xlen + kFooter || bsize > kMaxBlock)
        return -1;
      int64_t walk = sub + 6;
      while (walk < end) {
        if (walk + 4 > end) return -1;
        walk += 4 + u16(p + walk + 2);
      }
      if (walk != end) return -1;
      return bsize;
    }
    sub += 4 + slen;
  }
  return -1;
}

template <typename F>
void run_parallel(int64_t n, int threads, F&& fn) {
  if (threads <= 1 || n <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int64_t> next(0);
  auto worker = [&]() {
    for (;;) {
      const int64_t i = next.fetch_add(1);
      if (i >= n) return;
      fn(i);
    }
  };
  std::vector<std::thread> pool;
  const int k = threads < n ? threads : static_cast<int>(n);
  pool.reserve(k);
  for (int t = 0; t < k; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Walk the back-to-back block chain from `start`.  Fills up to `max_blocks`
// entries of (coffset, csize, usize); returns the count, or -1 on a malformed
// chain, or -2 if max_blocks was insufficient.
int64_t hbam_scan_blocks(const uint8_t* data, int64_t len, int64_t start,
                         int64_t* coffsets, int32_t* csizes, int32_t* usizes,
                         int64_t max_blocks) {
  int64_t pos = start, n = 0;
  while (pos < len) {
    const int64_t bsize = parse_header(data, len, pos);
    if (bsize < 0) return -1;
    if (pos + bsize > len) return -1;
    if (n >= max_blocks) return -2;
    const uint32_t usize = u32(data + pos + bsize - 4);
    if (usize > kMaxBlock) return -1;  // ISIZE beyond the BGZF bound
    coffsets[n] = pos;
    csizes[n] = static_cast<int32_t>(bsize);
    usizes[n] = static_cast<int32_t>(usize);
    ++n;
    pos += bsize;
  }
  return n;
}

// Scan for the next plausible block header at or after `start` (guesser
// fast path).  Returns the position, or -1 if none found before `end`.
int64_t hbam_find_next_block(const uint8_t* data, int64_t len, int64_t start,
                             int64_t end) {
  if (end > len) end = len;
  for (int64_t pos = start; pos < end; ++pos) {
    if (data[pos] != 0x1f) continue;
    const int64_t bsize = parse_header(data, len, pos);
    if (bsize >= 0 && pos + bsize <= len) return pos;
  }
  return -1;
}

// Batched block inflate.  Each block i occupies data[coffsets[i] ..
// coffsets[i]+csizes[i]) and inflates into out[out_offsets[i] ..).
// out_sizes[i] receives the payload size.  Returns 0, or (1+i) for a failure
// in block i (bad stream, ISIZE mismatch, or CRC error when check_crc).
int64_t hbam_inflate_blocks(const uint8_t* data, const int64_t* coffsets,
                            const int32_t* csizes, int64_t n, uint8_t* out,
                            const int64_t* out_offsets, int32_t* out_sizes,
                            int check_crc, int threads) {
  std::atomic<int64_t> err(0);
  run_parallel(n, threads, [&](int64_t i) {
    if (err.load(std::memory_order_relaxed)) return;
    const uint8_t* p = data + coffsets[i];
    const int64_t bsize = csizes[i];
    const int64_t xlen = u16(p + 10);
    const int64_t clen = bsize - kHeaderFixed - xlen - kFooter;
    if (clen < 0) { err = 1 + i; return; }
    const uint32_t want_crc = u32(p + bsize - 8);
    const uint32_t isize = u32(p + bsize - 4);
    z_stream zs;
    std::memset(&zs, 0, sizeof zs);
    if (inflateInit2(&zs, -15) != Z_OK) { err = 1 + i; return; }
    zs.next_in = const_cast<uint8_t*>(p + kHeaderFixed + xlen);
    zs.avail_in = static_cast<uInt>(clen);
    zs.next_out = out + out_offsets[i];
    // Bound writes to this block's reserved slot: a lying ISIZE must fail
    // the produced!=isize check below, not overflow into the next slot.
    zs.avail_out = static_cast<uInt>(out_offsets[i + 1] - out_offsets[i]);
    const int rc = inflate(&zs, Z_FINISH);
    const uint64_t produced = zs.total_out;
    inflateEnd(&zs);
    if (rc != Z_STREAM_END || produced != isize) { err = 1 + i; return; }
    if (check_crc) {
      const uint32_t got =
          crc32(0L, out + out_offsets[i], static_cast<uInt>(produced));
      if (got != want_crc) { err = 1 + i; return; }
    }
    out_sizes[i] = static_cast<int32_t>(produced);
  });
  return err.load();
}

// Batched BGZF block deflate.  Payload i is in[in_offsets[i] ..
// in_offsets[i+1]); the finished block lands at out + i*65536 with its size
// in out_sizes[i] (caller compacts).  Returns 0 or 1+i on failure.
int64_t hbam_deflate_blocks(const uint8_t* in, const int64_t* in_offsets,
                            int64_t n, int level, uint8_t* out,
                            int32_t* out_sizes, int threads) {
  std::atomic<int64_t> err(0);
  run_parallel(n, threads, [&](int64_t i) {
    if (err.load(std::memory_order_relaxed)) return;
    const uint8_t* payload = in + in_offsets[i];
    const int64_t plen = in_offsets[i + 1] - in_offsets[i];
    uint8_t* dst = out + i * kMaxBlock;
    for (int lvl = level;; lvl = 0) {
      z_stream zs;
      std::memset(&zs, 0, sizeof zs);
      if (deflateInit2(&zs, lvl, Z_DEFLATED, -15, 8, Z_DEFAULT_STRATEGY) !=
          Z_OK) { err = 1 + i; return; }
      zs.next_in = const_cast<uint8_t*>(payload);
      zs.avail_in = static_cast<uInt>(plen);
      zs.next_out = dst + kHeaderFixed + 6;
      zs.avail_out = static_cast<uInt>(kMaxBlock - kHeaderFixed - 6 - kFooter);
      const int rc = deflate(&zs, Z_FINISH);
      const int64_t clen = static_cast<int64_t>(zs.total_out);
      deflateEnd(&zs);
      if (rc == Z_STREAM_END) {
        const int64_t bsize = kHeaderFixed + 6 + clen + kFooter;
        // Header: magic, MTIME=0, XFL=0, OS=0xff, XLEN=6, BC subfield.
        const uint8_t hdr[18] = {0x1f, 0x8b, 0x08, 0x04, 0, 0, 0, 0,
                                 0,    0xff, 6,    0,    'B', 'C', 2, 0,
                                 static_cast<uint8_t>((bsize - 1) & 0xff),
                                 static_cast<uint8_t>(((bsize - 1) >> 8) & 0xff)};
        std::memcpy(dst, hdr, sizeof hdr);
        const uint32_t crc =
            crc32(0L, payload, static_cast<uInt>(plen));
        uint8_t* foot = dst + kHeaderFixed + 6 + clen;
        foot[0] = crc & 0xff; foot[1] = (crc >> 8) & 0xff;
        foot[2] = (crc >> 16) & 0xff; foot[3] = (crc >> 24) & 0xff;
        foot[4] = plen & 0xff; foot[5] = (plen >> 8) & 0xff;
        foot[6] = (plen >> 16) & 0xff; foot[7] = (plen >> 24) & 0xff;
        out_sizes[i] = static_cast<int32_t>(bsize);
        return;
      }
      if (lvl == 0) { err = 1 + i; return; }  // even stored didn't fit
    }
  });
  return err.load();
}

// Walk the BAM record chain (block_size-prefixed records) from `start` to
// `end` over an uncompressed byte stream.  Returns the record count, filling
// offs (or -1 if misaligned, -2 if max insufficient).
int64_t hbam_record_chain(const uint8_t* data, int64_t start, int64_t end,
                          int64_t* offs, int64_t max_records) {
  int64_t pos = start, n = 0;
  while (pos + 4 <= end) {
    const int64_t bs = u32(data + pos);
    if (n >= max_records) return -2;
    offs[n++] = pos;
    pos += 4 + bs;
  }
  if (pos != end) return -1;
  return n;
}

// Like hbam_record_chain but tolerates a truncated tail: stops before a
// record whose size word or body would run past `end`, and reports where
// the next (possibly incomplete) record starts via *resume so the caller
// can inflate spill blocks and continue the walk from there.
int64_t hbam_record_chain_partial(const uint8_t* data, int64_t start,
                                  int64_t end, int64_t* offs,
                                  int64_t max_records, int64_t* resume) {
  int64_t pos = start, n = 0;
  while (pos + 4 <= end) {
    const int64_t bs = u32(data + pos);
    if (pos + 4 + bs > end) break;
    if (n >= max_records) { *resume = pos; return -2; }
    offs[n++] = pos;
    pos += 4 + bs;
  }
  *resume = pos;
  return n;
}

// Gather records (block_size word + body) in permuted order into `out`.
// rec_off points at record *bodies* (the u32 size word sits 4 bytes before).
// Returns total bytes written.
// Prefetch distance for the permuted gathers: the copies jump to random
// record offsets, so each memcpy begins with a cold miss unless the source
// lines are requested a few iterations ahead (~30% on a 1-core host).
static const int64_t kGatherAhead = 8;

int64_t hbam_gather_records(const uint8_t* data, const int64_t* rec_off,
                            const int64_t* rec_len, const int64_t* order,
                            int64_t n, uint8_t* out) {
  int64_t w = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (i + kGatherAhead < n) {
      const int64_t p = order ? order[i + kGatherAhead] : i + kGatherAhead;
      __builtin_prefetch(data + rec_off[p] - 4, 0, 0);
      __builtin_prefetch(data + rec_off[p] - 4 + 64, 0, 0);
    }
    const int64_t r = order ? order[i] : i;
    const int64_t len = rec_len[r] + 4;
    std::memcpy(out + w, data + rec_off[r] - 4, len);
    w += len;
  }
  return w;
}

// Chunked variant: records live in several separate buffers (one per file
// split), addressed by (chunk_id, rec_off).  Lets the sort pipeline write
// permuted parts without ever concatenating the per-split payloads into one
// host buffer — on a 1-core host that concat was the single largest cost.
int64_t hbam_gather_records_chunked(const uint8_t* const* chunks,
                                    const int32_t* chunk_id,
                                    const int64_t* rec_off,
                                    const int64_t* rec_len,
                                    const int64_t* order, int64_t n,
                                    uint8_t* out) {
  int64_t w = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (i + kGatherAhead < n) {
      const int64_t p = order ? order[i + kGatherAhead] : i + kGatherAhead;
      const uint8_t* src = chunks[chunk_id[p]] + rec_off[p] - 4;
      __builtin_prefetch(src, 0, 0);
      __builtin_prefetch(src + 64, 0, 0);
    }
    const int64_t r = order ? order[i] : i;
    const int64_t len = rec_len[r] + 4;
    std::memcpy(out + w, chunks[chunk_id[r]] + rec_off[r] - 4, len);
    w += len;
  }
  return w;
}

// Ragged byte rows → 0-padded [n, width] matrix (the text tokenizers' SoA
// builder: FASTQ/QSEQ seq+qual lines).  One memcpy + memset per row,
// threaded; ~memory bandwidth instead of NumPy's fancy-index gather.
void hbam_gather_rows(const uint8_t* data, const int64_t* starts,
                      const int64_t* lens, int64_t n, int64_t width,
                      uint8_t* out, int threads) {
  run_parallel(n, threads, [&](int64_t i) {
    uint8_t* row = out + i * width;
    int64_t len = lens[i] < width ? lens[i] : width;
    if (len < 0) len = 0;  // negative length must never become a size_t
    std::memcpy(row, data + starts[i], len);
    if (len < width) std::memset(row + len, 0, width - len);
  });
}

// ---------------------------------------------------------------------------
// SAM text parse helpers: the memcpy-class inner loops of the vectorized
// SAM tokenizer (io/sam_vec.py).  NumPy owns tokenization and validation
// structure; these functions replace its index-array scatters with threaded
// per-record loops.  All return 0 on success, 1 when any row needs the
// exact per-line parser (the caller falls back for the whole split).
// ---------------------------------------------------------------------------

int64_t hbam_parse_i64(const uint8_t* data, const int64_t* starts,
                       const int64_t* lens, int64_t n, int64_t* out,
                       int threads) {
  std::atomic<int64_t> fail(0);
  run_parallel(n, threads, [&](int64_t i) {
    const uint8_t* p = data + starts[i];
    int64_t len = lens[i];
    if (len <= 0 || len > 19) { fail.store(1); out[i] = 0; return; }
    int64_t k = 0;
    bool neg = p[0] == '-';
    if (neg) k = 1;
    // 18 digits max: 19 could overflow int64 in v*10+d (signed UB).
    if (k >= len || len - k > 18) { fail.store(1); out[i] = 0; return; }
    int64_t v = 0;
    for (; k < len; ++k) {
      const uint8_t c = p[k];
      if (c < '0' || c > '9') { fail.store(1); out[i] = 0; return; }
      v = v * 10 + (c - '0');
    }
    out[i] = neg ? -v : v;
  });
  return fail.load();
}

namespace {
constexpr const char kCigarOps[] = "MIDNSHP=X";
int8_t cigar_code(uint8_t c) {
  for (int k = 0; k < 9; ++k)
    if (kCigarOps[k] == c) return static_cast<int8_t>(k);
  return -1;
}
// Ops consuming reference bases (span for reg2bin): M D N = X
constexpr uint16_t kCigarRefMask = (1u << 0) | (1u << 2) | (1u << 3) |
                                   (1u << 7) | (1u << 8);
}  // namespace

// Pass 1 (opvals == nullptr): validate + count ops + reference span.
// Pass 2 (opvals != nullptr): fill BAM-encoded (len<<4|op) u32s at op_off.
int64_t hbam_parse_cigars(const uint8_t* data, const int64_t* starts,
                          const int64_t* lens, int64_t n, int64_t* n_ops,
                          int64_t* span, const int64_t* op_off,
                          uint32_t* opvals, int threads) {
  std::atomic<int64_t> fail(0);
  run_parallel(n, threads, [&](int64_t i) {
    const uint8_t* p = data + starts[i];
    const int64_t len = lens[i];
    if (len <= 0) { fail.store(1); return; }
    if (len == 1 && p[0] == '*') {
      if (opvals == nullptr) { n_ops[i] = 0; span[i] = 0; }
      return;
    }
    uint32_t* dst = opvals ? opvals + op_off[i] : nullptr;
    int64_t ops = 0, sp = 0, k = 0;
    while (k < len) {
      int64_t d = 0, v = 0;
      while (k < len && p[k] >= '0' && p[k] <= '9') {
        v = v * 10 + (p[k] - '0');
        ++k; ++d;
      }
      if (d == 0 || d > 9 || v >= (1 << 28) || k >= len) {
        fail.store(1);
        return;
      }
      const int8_t code = cigar_code(p[k]);
      if (code < 0) { fail.store(1); return; }
      ++k;
      if (dst) dst[ops] = (static_cast<uint32_t>(v) << 4) | code;
      if (kCigarRefMask & (1u << code)) sp += v;
      ++ops;
    }
    if (opvals == nullptr) { n_ops[i] = ops; span[i] = sp; }
  });
  return fail.load();
}

namespace {
struct SeqLut {
  uint8_t t[256];
  SeqLut() {
    for (int i = 0; i < 256; ++i) t[i] = 15;
    const char* alphabet = "=ACMGRSVTWYHKDBN";
    for (int i = 0; i < 16; ++i) {
      t[static_cast<uint8_t>(alphabet[i])] = i;
      t[static_cast<uint8_t>(std::tolower(alphabet[i]))] = i;
    }
  }
};
const SeqLut kSeqLut;
}  // namespace

// Assemble every binary SAM record in one threaded pass: fixed fields,
// name+NUL, CIGAR u32s, packed SEQ nibbles, QUAL (-33 or 0xFF fill), tags.
// Every output byte is written (callers may pass uninitialized memory).
// Returns 1 if any QUAL byte is < '!' (exact path errors).
int64_t hbam_sam_emit(
    const uint8_t* text, int64_t n, const int64_t* rec_off,
    const int64_t* body_len, const int32_t* refid, const int32_t* pos0,
    const int32_t* mapq, const int32_t* bin, const int32_t* n_ops,
    const int32_t* flag, const int32_t* l_seq, const int32_t* nrefid,
    const int32_t* npos0, const int32_t* tlen, const int64_t* name_src,
    const int64_t* name_len, const int64_t* op_off, const uint32_t* opvals,
    const int64_t* seq_src, const uint8_t* seq_star, const int64_t* qual_src,
    const int64_t* qual_len, const uint8_t* qual_star,
    const int64_t* tag_off, const int64_t* tag_len, const uint8_t* tag_blob,
    uint8_t* out, int threads) {
  std::atomic<int64_t> fail(0);
  run_parallel(n, threads, [&](int64_t i) {
    uint8_t* r = out + rec_off[i];
    auto w32 = [](uint8_t* p, uint32_t v) { std::memcpy(p, &v, 4); };
    auto w16 = [](uint8_t* p, uint16_t v) { std::memcpy(p, &v, 2); };
    w32(r, static_cast<uint32_t>(body_len[i]));
    uint8_t* b = r + 4;
    w32(b + 0, static_cast<uint32_t>(refid[i]));
    w32(b + 4, static_cast<uint32_t>(pos0[i]));
    b[8] = static_cast<uint8_t>(name_len[i] + 1);
    b[9] = static_cast<uint8_t>(mapq[i]);
    w16(b + 10, static_cast<uint16_t>(bin[i]));
    w16(b + 12, static_cast<uint16_t>(n_ops[i]));
    w16(b + 14, static_cast<uint16_t>(flag[i]));
    w32(b + 16, static_cast<uint32_t>(l_seq[i]));
    w32(b + 20, static_cast<uint32_t>(nrefid[i]));
    w32(b + 24, static_cast<uint32_t>(npos0[i]));
    w32(b + 28, static_cast<uint32_t>(tlen[i]));
    uint8_t* p = b + 32;
    std::memcpy(p, text + name_src[i], name_len[i]);
    p[name_len[i]] = 0;
    p += name_len[i] + 1;
    std::memcpy(p, opvals + op_off[i], 4 * n_ops[i]);
    p += 4 * n_ops[i];
    const int64_t ls = l_seq[i];
    if (!seq_star[i] && ls > 0) {
      const uint8_t* s = text + seq_src[i];
      int64_t j = 0;
      for (; j + 1 < ls; j += 2)
        p[j >> 1] = (kSeqLut.t[s[j]] << 4) | kSeqLut.t[s[j + 1]];
      if (j < ls) p[j >> 1] = kSeqLut.t[s[j]] << 4;
    }
    p += (ls + 1) / 2;
    if (qual_star[i]) {
      std::memset(p, 0xFF, ls);
      p += ls;
    } else {
      const uint8_t* q = text + qual_src[i];
      const int64_t ql = qual_len[i];
      for (int64_t j = 0; j < ql; ++j) {
        if (q[j] < 33) { fail.store(1); return; }
        p[j] = q[j] - 33;
      }
      p += ql;
    }
    std::memcpy(p, tag_blob + tag_off[i], tag_len[i]);
  });
  return fail.load();
}

namespace {
// Strict decimal int parse over [p, p+len); returns false on anything
// Python's int() would accept but this doesn't (caller bails to the exact
// parser — a strict subset keeps byte-equivalence).
bool parse_int_strict(const uint8_t* p, int64_t len, int64_t* out) {
  // 18 digits max: 19 could overflow int64 in v*10+d (signed UB).
  if (len <= 0 || len > 19) return false;
  if (len - ((p[0] == '-') ? 1 : 0) > 18) return false;
  int64_t k = (p[0] == '-') ? 1 : 0;
  if (k >= len) return false;
  int64_t v = 0;
  for (; k < len; ++k) {
    if (p[k] < '0' || p[k] > '9') return false;
    v = v * 10 + (p[k] - '0');
  }
  *out = (p[0] == '-') ? -v : v;
  return true;
}

int tag_int_width(int64_t v, uint8_t* code) {
  if (v >= -128 && v <= 127) { *code = 'c'; return 1; }
  if (v >= 0 && v <= 255) { *code = 'C'; return 1; }
  if (v >= -32768 && v <= 32767) { *code = 's'; return 2; }
  if (v >= 0 && v <= 65535) { *code = 'S'; return 2; }
  if (v >= INT64_C(-2147483648) && v <= INT64_C(2147483647)) {
    *code = 'i'; return 4;
  }
  if (v >= 0 && v <= INT64_C(4294967295)) { *code = 'I'; return 4; }
  return 0;  // out of u32 range: exact path raises
}

int b_elem_size(uint8_t e) {
  switch (e) {
    case 'c': case 'C': return 1;
    case 's': case 'S': return 2;
    case 'i': case 'I': case 'f': return 4;
    default: return 0;
  }
}

bool b_elem_range(uint8_t e, int64_t v) {
  switch (e) {
    case 'c': return v >= -128 && v <= 127;
    case 'C': return v >= 0 && v <= 255;
    case 's': return v >= -32768 && v <= 32767;
    case 'S': return v >= 0 && v <= 65535;
    case 'i': return v >= INT64_C(-2147483648) && v <= INT64_C(2147483647);
    case 'I': return v >= 0 && v <= INT64_C(4294967295);
    default: return false;
  }
}

// Parse a float value the way Python's float() + struct.pack('<f') does:
// decimal → double (strtod) → float (the same double rounding).  Any form
// where strtod and Python float() could diverge — hex floats ("0x1p3",
// "-0X2"), nan payloads ("nan(1)"), whitespace — fails instead, sending
// the token to the exact encoder (strict subset keeps byte-equivalence).
bool parse_f32(const uint8_t* p, int64_t len, float* out) {
  if (len <= 0 || len > 63) return false;
  char buf[64];
  for (int64_t i = 0; i < len; ++i) {
    const uint8_t c = p[i];
    if (c == 'x' || c == 'X' || c == '(' || c == ' ' || c == '\t')
      return false;
    buf[i] = static_cast<char>(c);
  }
  buf[len] = 0;
  char* end = nullptr;
  double d = std::strtod(buf, &end);
  if (end != buf + len) return false;
  const float f = static_cast<float>(d);
  // A finite double overflowing to float inf: struct.pack('<f') raises
  // OverflowError — the exact encoder must own that error.
  if (std::isfinite(d) && !std::isfinite(f)) return false;
  *out = f;
  return true;
}
}  // namespace

// SAM tag tokens → binary BAM tag encoding, two passes like
// hbam_parse_cigars: pass 1 (blob == nullptr) computes enc_len per token
// (validating); pass 2 emits at dst[t].  Tokens are TAG:T:VALUE with
// len >= 5 (caller pre-filters).  Returns 0 ok, 1 bail-to-exact-path.
int64_t hbam_encode_tags(const uint8_t* text, const int64_t* starts,
                         const int64_t* lens, int64_t n, int64_t* enc_len,
                         const int64_t* dst, uint8_t* blob, int threads) {
  std::atomic<int64_t> fail(0);
  run_parallel(n, threads, [&](int64_t t) {
    const uint8_t* p = text + starts[t];
    const int64_t len = lens[t];
    const uint8_t typ = p[3];
    const uint8_t* v = p + 5;
    const int64_t vlen = len - 5;
    uint8_t* o = blob ? blob + dst[t] : nullptr;
    if (o) { o[0] = p[0]; o[1] = p[1]; o[2] = typ; }
    switch (typ) {
      case 'A': {
        if (!o) { enc_len[t] = 3 + (vlen > 0 ? 1 : 0); return; }
        if (vlen > 0) o[3] = v[0];
        return;
      }
      case 'i': {
        int64_t iv;
        uint8_t code;
        if (!parse_int_strict(v, vlen, &iv)) { fail.store(1); return; }
        const int w = tag_int_width(iv, &code);
        if (w == 0) { fail.store(1); return; }
        if (!o) { enc_len[t] = 3 + w; return; }
        o[2] = code;
        for (int b = 0; b < w; ++b) o[3 + b] = (iv >> (8 * b)) & 0xFF;
        return;
      }
      case 'f': {
        float f;
        if (!parse_f32(v, vlen, &f)) { fail.store(1); return; }
        if (!o) { enc_len[t] = 7; return; }
        std::memcpy(o + 3, &f, 4);
        return;
      }
      case 'Z':
      case 'H': {
        if (!o) { enc_len[t] = 3 + vlen + 1; return; }
        std::memcpy(o + 3, v, vlen);
        o[3 + vlen] = 0;
        return;
      }
      case 'B': {
        if (vlen < 1) { fail.store(1); return; }
        const uint8_t elem = v[0];
        const int es = b_elem_size(elem);
        if (es == 0) { fail.store(1); return; }
        // Count and validate comma-separated values.
        int64_t count = 0, k = 1;
        uint8_t* w = o ? o + 8 : nullptr;
        while (k < vlen) {
          if (v[k] != ',') { fail.store(1); return; }
          ++k;
          int64_t e = k;
          while (e < vlen && v[e] != ',') ++e;
          if (elem == 'f') {
            float f;
            if (!parse_f32(v + k, e - k, &f)) { fail.store(1); return; }
            if (w) { std::memcpy(w, &f, 4); w += 4; }
          } else {
            int64_t iv;
            if (!parse_int_strict(v + k, e - k, &iv) ||
                !b_elem_range(elem, iv)) {
              fail.store(1);
              return;
            }
            if (w) {
              for (int b = 0; b < es; ++b) w[b] = (iv >> (8 * b)) & 0xFF;
              w += es;
            }
          }
          ++count;
          k = e;
        }
        if (!o) { enc_len[t] = 3 + 1 + 4 + count * es; return; }
        o[3] = elem;
        const uint32_t c32 = static_cast<uint32_t>(count);
        std::memcpy(o + 4, &c32, 4);
        return;
      }
      default:
        fail.store(1);  // unknown type: exact path raises SamError
        return;
    }
  });
  return fail.load();
}

int64_t hbam_count_byte(const uint8_t* text, int64_t start, int64_t end,
                        int needle) {
  int64_t n = 0;
  const uint8_t* p = text + start;
  const uint8_t* const e = text + end;
  while (p < e) {
    const uint8_t* hit =
        static_cast<const uint8_t*>(std::memchr(p, needle, e - p));
    if (!hit) break;
    ++n;
    p = hit + 1;
  }
  return n;
}

// One serial memchr-paced pass over the SAM lines of [lo, hi): the line
// table, the 11-field table, the five core integer fields, and the tag
// token table (row-major, tokens < 5 bytes skipped like the exact parser).
// Header ('@') and empty lines are skipped.  Outputs are sized by the
// caller from hbam_count_byte bounds.  counts[0]=lines, counts[1]=tokens.
// Returns 0 ok; 1 when any line needs the exact parser (field count < 11,
// non-decimal core field, line cut off by window_end when more file
// follows).
int64_t hbam_sam_scan(
    const uint8_t* text, int64_t len, int64_t lo, int64_t hi,
    int64_t window_end, int64_t* counts, int64_t* ints /* [5*cap] */,
    int64_t* name_src, int64_t* name_len, int64_t* rname_src,
    int64_t* rname_len, int64_t* cigar_src, int64_t* cigar_len,
    int64_t* rnext_src, int64_t* rnext_len, int64_t* seq_src,
    int64_t* seq_len, int64_t* qual_src, int64_t* qual_len,
    int64_t* tok_start, int64_t* tok_len, int64_t* tok_rid,
    int64_t line_cap, int64_t tok_cap) {
  int64_t n = 0, T = 0;
  int64_t p = lo;
  while (p < hi && p < len) {
    const uint8_t* nl = static_cast<const uint8_t*>(
        std::memchr(text + p, '\n', window_end - p));
    int64_t e = nl ? (nl - text) : window_end;
    const int64_t next = e + 1;
    if (!nl && window_end < len) return 1;  // cut off by the scan window
    if (e > p && text[e - 1] == '\r') --e;
    if (e == p || text[p] == '@') {  // empty or header line
      p = next;
      continue;
    }
    if (n >= line_cap) return 1;
    // 11 fields split on the first 10 tabs.
    int64_t fs[12];
    fs[0] = p;
    int64_t k = 1;
    const uint8_t* q = text + p;
    const uint8_t* const qe = text + e;
    while (k <= 10) {
      const uint8_t* t =
          static_cast<const uint8_t*>(std::memchr(q, '\t', qe - q));
      if (!t) break;
      fs[k++] = (t - text) + 1;
      q = t + 1;
    }
    if (k <= 10) return 1;  // < 11 fields
    // Field 10 (QUAL) ends at the next tab (tags follow) or line end.
    const uint8_t* t10 =
        static_cast<const uint8_t*>(std::memchr(q, '\t', qe - q));
    const int64_t f10_end = t10 ? (t10 - text) : e;
    // Core integers: flag(1) pos(3) mapq(4) pnext(7) tlen(8).
    static const int kIntField[5] = {1, 3, 4, 7, 8};
    for (int c = 0; c < 5; ++c) {
      const int f = kIntField[c];
      const int64_t fe = fs[f + 1] - 1;
      if (!parse_int_strict(text + fs[f], fe - fs[f], &ints[5 * n + c]))
        return 1;
    }
    // QNAME ('*' → empty name).
    const int64_t ql = fs[1] - 1 - fs[0];
    name_src[n] = fs[0];
    name_len[n] = (ql == 1 && text[fs[0]] == '*') ? 0 : ql;
    rname_src[n] = fs[2];
    rname_len[n] = fs[3] - 1 - fs[2];
    cigar_src[n] = fs[5];
    cigar_len[n] = fs[6] - 1 - fs[5];
    rnext_src[n] = fs[6];
    rnext_len[n] = fs[7] - 1 - fs[6];
    seq_src[n] = fs[9];
    seq_len[n] = fs[10] - 1 - fs[9];
    qual_src[n] = fs[10];
    qual_len[n] = f10_end - fs[10];
    // Tag tokens after field 10.
    if (t10) {
      const uint8_t* r = t10 + 1;
      while (r <= qe) {
        const uint8_t* t =
            static_cast<const uint8_t*>(std::memchr(r, '\t', qe - r));
        const uint8_t* te = t ? t : qe;
        const int64_t tl = te - r;
        if (tl >= 5) {
          if (T >= tok_cap) return 1;
          tok_start[T] = r - text;
          tok_len[T] = tl;
          tok_rid[T] = n;
          ++T;
        }
        if (!t) break;
        r = t + 1;
      }
    }
    ++n;
    p = next;
  }
  counts[0] = n;
  counts[1] = T;
  return 0;
}

namespace {
// One BCF typed value, mirroring spec/bcf.py read_typed_value's accepted
// forms CONSERVATIVELY: any deviation (bad type code, nonstandard len-15
// extension, missing/EOV where a scalar is required) reports failure and
// the caller falls back to the exact decoder, whose error semantics are
// the contract.  On success *p advances past the value.
struct TypedVal {
  int t = 0;        // type code
  int64_t len = 0;  // element count
  int64_t at = 0;   // first payload byte
  int64_t first = 0;     // first element (int types only)
  bool first_ok = false; // first element present and not MISSING/EOV
};

bool bcf_typed_skip(const uint8_t* b, int64_t limit, int64_t* p,
                    TypedVal* out) {
  if (*p + 1 > limit) return false;
  const uint8_t d = b[(*p)++];
  int t = d & 0xF;
  int64_t ln = d >> 4;
  if (ln == 15) {
    // Length extension: a nested typed scalar (int types only here).
    if (*p + 1 > limit) return false;
    const uint8_t d2 = b[(*p)++];
    const int t2 = d2 & 0xF;
    const int64_t ln2 = d2 >> 4;
    if (ln2 < 1) return false;
    int64_t v = 0;
    if (t2 == 1) {
      if (*p + ln2 > limit) return false;
      v = static_cast<int8_t>(b[*p]);
      *p += ln2;
    } else if (t2 == 2) {
      if (*p + 2 * ln2 > limit) return false;
      int16_t x;
      std::memcpy(&x, b + *p, 2);
      v = x;
      *p += 2 * ln2;
    } else if (t2 == 3) {
      if (*p + 4 * ln2 > limit) return false;
      int32_t x;
      std::memcpy(&x, b + *p, 4);
      v = x;
      *p += 4 * ln2;
    } else {
      return false;
    }
    if (v < 0) return false;
    ln = v;
  }
  out->t = t;
  out->len = ln;
  out->at = *p;
  out->first_ok = false;
  if (t == 0) return true;  // MISSING: no payload consumed
  int64_t esize;
  switch (t) {
    case 1: esize = 1; break;
    case 2: esize = 2; break;
    case 3: esize = 4; break;
    case 5: esize = 4; break;  // float
    case 7: esize = 1; break;  // char
    default: return false;     // the exact decoder raises "bad int type"
  }
  if (*p + esize * ln > limit) return false;
  if (ln > 0 && (t == 1 || t == 2 || t == 3)) {
    int64_t v = 0;
    bool ok = true;
    if (t == 1) {
      const int8_t x = static_cast<int8_t>(b[*p]);
      v = x;
      ok = x != -128 && x != -127;  // MISSING / EOV
    } else if (t == 2) {
      int16_t x;
      std::memcpy(&x, b + *p, 2);
      v = x;
      ok = x != -32768 && x != -32767;
    } else {
      int32_t x;
      std::memcpy(&x, b + *p, 4);
      v = x;
      ok = x != INT32_MIN && x != INT32_MIN + 1;
    }
    out->first = v;
    out->first_ok = ok;
  }
  *p += esize * ln;
  return true;
}
}  // namespace

// BCF record scan: chain walk + full shared-block validation in one pass.
// Records are [u32 l_shared][u32 l_indiv][body] back to back; start
// offsets of records beginning in [start, end) append to offsets, with
// ref_len[i] = length of the REF allele and end_info[i] = the INFO END
// value (INT64_MIN when absent/non-scalar — matching the exact decoder's
// END= text-regex rule).  The shared block's typed values are walked and
// bounds/type-checked against the header dictionary sizes, so a clean
// return means the exact decoder would accept every record.  Returns the
// count, -1 when anything needs the exact path, -2 when cap is too small.
int64_t hbam_bcf_scan(const uint8_t* data, int64_t len, int64_t start,
                      int64_t end, int64_t n_contigs, int64_t n_strings,
                      int64_t end_key, int64_t* offsets, int64_t* ref_len,
                      int64_t* end_info, int64_t cap) {
  int64_t p = start, n = 0;
  while (p + 8 <= end) {
    if (p + 8 > len) return -1;
    uint32_t ls, li;
    std::memcpy(&ls, data + p, 4);
    std::memcpy(&li, data + p + 4, 4);
    const int64_t body = p + 8;
    const int64_t next =
        body + static_cast<int64_t>(ls) + static_cast<int64_t>(li);
    if (ls < 24 || next > len) return -1;
    if (n >= cap) return -2;
    const int64_t limit = body + ls;
    int32_t chrom, nai, nfs;
    std::memcpy(&chrom, data + body, 4);
    std::memcpy(&nai, data + body + 16, 4);
    std::memcpy(&nfs, data + body + 20, 4);
    if (chrom < 0 || chrom >= n_contigs) return -1;
    const int64_t n_allele = static_cast<uint32_t>(nai) >> 16;
    const int64_t n_info = static_cast<uint32_t>(nai) & 0xFFFF;
    int64_t q = body + 24;
    TypedVal tv;
    if (!bcf_typed_skip(data, limit, &q, &tv)) return -1;  // ID
    int64_t rl = 1;  // n_allele == 0 → REF "N" → length 1
    for (int64_t k = 0; k < n_allele; ++k) {
      if (!bcf_typed_skip(data, limit, &q, &tv)) return -1;
      if (k == 0) {
        if (tv.t != 7 || tv.len <= 0) return -1;  // REF must be chars
        rl = tv.len;
      }
    }
    // FILTER: int vector (or missing); every entry a valid string index.
    if (!bcf_typed_skip(data, limit, &q, &tv)) return -1;
    if (tv.t != 0) {
      if (tv.t != 1 && tv.t != 2 && tv.t != 3) return -1;
      const int64_t es = tv.t == 1 ? 1 : tv.t == 2 ? 2 : 4;
      for (int64_t k = 0; k < tv.len; ++k) {
        int64_t v;
        if (tv.t == 1)
          v = static_cast<int8_t>(data[tv.at + k]);
        else if (tv.t == 2) {
          int16_t x;
          std::memcpy(&x, data + tv.at + 2 * k, 2);
          v = x;
        } else {
          int32_t x;
          std::memcpy(&x, data + tv.at + 4 * k, 4);
          v = x;
        }
        const int64_t missing = es == 1 ? -128 : es == 2 ? -32768
                                               : INT64_C(-2147483648);
        if (v == missing) continue;  // skipped by the exact decoder
        if (v == missing + 1) break;  // EOV terminates
        if (v < 0 || v >= n_strings) return -1;
      }
    }
    int64_t endv = INT64_MIN;
    for (int64_t k = 0; k < n_info; ++k) {
      TypedVal key;
      if (!bcf_typed_skip(data, limit, &q, &key)) return -1;
      // Key must be an int scalar-first with a live value in range.
      if (!(key.t == 1 || key.t == 2 || key.t == 3) || key.len < 1 ||
          !key.first_ok || key.first < 0 || key.first >= n_strings)
        return -1;
      TypedVal val;
      if (!bcf_typed_skip(data, limit, &q, &val)) return -1;
      // INFO END override: the exact path's END= regex matches only the
      // "END=<int>" rendering.  A clean int scalar overrides; a MISSING
      // value renders as a bare flag (no override); anything else (float
      // END, vectors, missing-first) could render regex-matchable text —
      // bail so the exact decoder decides.
      if (key.first == end_key) {
        if ((val.t == 1 || val.t == 2 || val.t == 3) && val.len == 1 &&
            val.first_ok) {
          if (endv == INT64_MIN) endv = val.first;
        } else if (val.t != 0) {
          return -1;
        }
      }
    }
    if (q != limit) return -1;  // shared-length mismatch: exact raises
    offsets[n] = p;
    ref_len[n] = rl;
    end_info[n] = endv;
    ++n;
    p = next;
  }
  return n;
}

int hbam_abi_version() { return 6; }

}  // extern "C"
