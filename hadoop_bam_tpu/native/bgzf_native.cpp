// Native host path: batched BGZF block codec + BAM record chain walking.
//
// The reference delegates its hot host work to htsjdk's native zlib
// (BlockCompressedInputStream / BAMRecordCodec below reference L0).  This
// library is the TPU build's equivalent: block-granular batched
// inflate/deflate with an internal thread pool, BGZF header scanning with the
// split-guesser's candidate rules (BaseSplitGuesser.java:31-108 semantics),
// and the serial BAM record-boundary walk (the part that cannot be
// vectorized until offsets are known; SURVEY.md §7 stage 4).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

constexpr int64_t kHeaderFixed = 12;  // gzip header incl. XLEN
constexpr int64_t kFooter = 8;        // CRC32 + ISIZE
constexpr int64_t kMaxBlock = 0x10000;

inline uint16_t u16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0]) | (static_cast<uint16_t>(p[1]) << 8);
}
inline uint32_t u32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

// Parse a BGZF block header at data[pos]; returns total block size (bsize) or
// -1.  Mirrors the subfield walk incl. the exact-XLEN-landing cancellation
// (BaseSplitGuesser.java:80-90).
int64_t parse_header(const uint8_t* data, int64_t len, int64_t pos) {
  if (pos + kHeaderFixed > len) return -1;
  const uint8_t* p = data + pos;
  if (p[0] != 0x1f || p[1] != 0x8b || p[2] != 0x08 || p[3] != 0x04) return -1;
  const int64_t xlen = u16(p + 10);
  if (pos + kHeaderFixed + xlen > len) return -1;
  int64_t sub = kHeaderFixed;
  const int64_t end = kHeaderFixed + xlen;
  while (sub + 4 <= end) {
    const uint16_t slen = u16(p + sub + 2);
    if (p[sub] == 'B' && p[sub + 1] == 'C' && slen == 2) {
      if (sub + 6 > end) return -1;
      const int64_t bsize = static_cast<int64_t>(u16(p + sub + 4)) + 1;
      if (bsize < kHeaderFixed + xlen + kFooter || bsize > kMaxBlock)
        return -1;
      int64_t walk = sub + 6;
      while (walk < end) {
        if (walk + 4 > end) return -1;
        walk += 4 + u16(p + walk + 2);
      }
      if (walk != end) return -1;
      return bsize;
    }
    sub += 4 + slen;
  }
  return -1;
}

template <typename F>
void run_parallel(int64_t n, int threads, F&& fn) {
  if (threads <= 1 || n <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int64_t> next(0);
  auto worker = [&]() {
    for (;;) {
      const int64_t i = next.fetch_add(1);
      if (i >= n) return;
      fn(i);
    }
  };
  std::vector<std::thread> pool;
  const int k = threads < n ? threads : static_cast<int>(n);
  pool.reserve(k);
  for (int t = 0; t < k; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Walk the back-to-back block chain from `start`.  Fills up to `max_blocks`
// entries of (coffset, csize, usize); returns the count, or -1 on a malformed
// chain, or -2 if max_blocks was insufficient.
int64_t hbam_scan_blocks(const uint8_t* data, int64_t len, int64_t start,
                         int64_t* coffsets, int32_t* csizes, int32_t* usizes,
                         int64_t max_blocks) {
  int64_t pos = start, n = 0;
  while (pos < len) {
    const int64_t bsize = parse_header(data, len, pos);
    if (bsize < 0) return -1;
    if (pos + bsize > len) return -1;
    if (n >= max_blocks) return -2;
    const uint32_t usize = u32(data + pos + bsize - 4);
    if (usize > kMaxBlock) return -1;  // ISIZE beyond the BGZF bound
    coffsets[n] = pos;
    csizes[n] = static_cast<int32_t>(bsize);
    usizes[n] = static_cast<int32_t>(usize);
    ++n;
    pos += bsize;
  }
  return n;
}

// Scan for the next plausible block header at or after `start` (guesser
// fast path).  Returns the position, or -1 if none found before `end`.
int64_t hbam_find_next_block(const uint8_t* data, int64_t len, int64_t start,
                             int64_t end) {
  if (end > len) end = len;
  for (int64_t pos = start; pos < end; ++pos) {
    if (data[pos] != 0x1f) continue;
    const int64_t bsize = parse_header(data, len, pos);
    if (bsize >= 0 && pos + bsize <= len) return pos;
  }
  return -1;
}

// Batched block inflate.  Each block i occupies data[coffsets[i] ..
// coffsets[i]+csizes[i]) and inflates into out[out_offsets[i] ..).
// out_sizes[i] receives the payload size.  Returns 0, or (1+i) for a failure
// in block i (bad stream, ISIZE mismatch, or CRC error when check_crc).
int64_t hbam_inflate_blocks(const uint8_t* data, const int64_t* coffsets,
                            const int32_t* csizes, int64_t n, uint8_t* out,
                            const int64_t* out_offsets, int32_t* out_sizes,
                            int check_crc, int threads) {
  std::atomic<int64_t> err(0);
  run_parallel(n, threads, [&](int64_t i) {
    if (err.load(std::memory_order_relaxed)) return;
    const uint8_t* p = data + coffsets[i];
    const int64_t bsize = csizes[i];
    const int64_t xlen = u16(p + 10);
    const int64_t clen = bsize - kHeaderFixed - xlen - kFooter;
    if (clen < 0) { err = 1 + i; return; }
    const uint32_t want_crc = u32(p + bsize - 8);
    const uint32_t isize = u32(p + bsize - 4);
    z_stream zs;
    std::memset(&zs, 0, sizeof zs);
    if (inflateInit2(&zs, -15) != Z_OK) { err = 1 + i; return; }
    zs.next_in = const_cast<uint8_t*>(p + kHeaderFixed + xlen);
    zs.avail_in = static_cast<uInt>(clen);
    zs.next_out = out + out_offsets[i];
    // Bound writes to this block's reserved slot: a lying ISIZE must fail
    // the produced!=isize check below, not overflow into the next slot.
    zs.avail_out = static_cast<uInt>(out_offsets[i + 1] - out_offsets[i]);
    const int rc = inflate(&zs, Z_FINISH);
    const uint64_t produced = zs.total_out;
    inflateEnd(&zs);
    if (rc != Z_STREAM_END || produced != isize) { err = 1 + i; return; }
    if (check_crc) {
      const uint32_t got =
          crc32(0L, out + out_offsets[i], static_cast<uInt>(produced));
      if (got != want_crc) { err = 1 + i; return; }
    }
    out_sizes[i] = static_cast<int32_t>(produced);
  });
  return err.load();
}

// Batched BGZF block deflate.  Payload i is in[in_offsets[i] ..
// in_offsets[i+1]); the finished block lands at out + i*65536 with its size
// in out_sizes[i] (caller compacts).  Returns 0 or 1+i on failure.
int64_t hbam_deflate_blocks(const uint8_t* in, const int64_t* in_offsets,
                            int64_t n, int level, uint8_t* out,
                            int32_t* out_sizes, int threads) {
  std::atomic<int64_t> err(0);
  run_parallel(n, threads, [&](int64_t i) {
    if (err.load(std::memory_order_relaxed)) return;
    const uint8_t* payload = in + in_offsets[i];
    const int64_t plen = in_offsets[i + 1] - in_offsets[i];
    uint8_t* dst = out + i * kMaxBlock;
    for (int lvl = level;; lvl = 0) {
      z_stream zs;
      std::memset(&zs, 0, sizeof zs);
      if (deflateInit2(&zs, lvl, Z_DEFLATED, -15, 8, Z_DEFAULT_STRATEGY) !=
          Z_OK) { err = 1 + i; return; }
      zs.next_in = const_cast<uint8_t*>(payload);
      zs.avail_in = static_cast<uInt>(plen);
      zs.next_out = dst + kHeaderFixed + 6;
      zs.avail_out = static_cast<uInt>(kMaxBlock - kHeaderFixed - 6 - kFooter);
      const int rc = deflate(&zs, Z_FINISH);
      const int64_t clen = static_cast<int64_t>(zs.total_out);
      deflateEnd(&zs);
      if (rc == Z_STREAM_END) {
        const int64_t bsize = kHeaderFixed + 6 + clen + kFooter;
        // Header: magic, MTIME=0, XFL=0, OS=0xff, XLEN=6, BC subfield.
        const uint8_t hdr[18] = {0x1f, 0x8b, 0x08, 0x04, 0, 0, 0, 0,
                                 0,    0xff, 6,    0,    'B', 'C', 2, 0,
                                 static_cast<uint8_t>((bsize - 1) & 0xff),
                                 static_cast<uint8_t>(((bsize - 1) >> 8) & 0xff)};
        std::memcpy(dst, hdr, sizeof hdr);
        const uint32_t crc =
            crc32(0L, payload, static_cast<uInt>(plen));
        uint8_t* foot = dst + kHeaderFixed + 6 + clen;
        foot[0] = crc & 0xff; foot[1] = (crc >> 8) & 0xff;
        foot[2] = (crc >> 16) & 0xff; foot[3] = (crc >> 24) & 0xff;
        foot[4] = plen & 0xff; foot[5] = (plen >> 8) & 0xff;
        foot[6] = (plen >> 16) & 0xff; foot[7] = (plen >> 24) & 0xff;
        out_sizes[i] = static_cast<int32_t>(bsize);
        return;
      }
      if (lvl == 0) { err = 1 + i; return; }  // even stored didn't fit
    }
  });
  return err.load();
}

// Walk the BAM record chain (block_size-prefixed records) from `start` to
// `end` over an uncompressed byte stream.  Returns the record count, filling
// offs (or -1 if misaligned, -2 if max insufficient).
int64_t hbam_record_chain(const uint8_t* data, int64_t start, int64_t end,
                          int64_t* offs, int64_t max_records) {
  int64_t pos = start, n = 0;
  while (pos + 4 <= end) {
    const int64_t bs = u32(data + pos);
    if (n >= max_records) return -2;
    offs[n++] = pos;
    pos += 4 + bs;
  }
  if (pos != end) return -1;
  return n;
}

// Like hbam_record_chain but tolerates a truncated tail: stops before a
// record whose size word or body would run past `end`, and reports where
// the next (possibly incomplete) record starts via *resume so the caller
// can inflate spill blocks and continue the walk from there.
int64_t hbam_record_chain_partial(const uint8_t* data, int64_t start,
                                  int64_t end, int64_t* offs,
                                  int64_t max_records, int64_t* resume) {
  int64_t pos = start, n = 0;
  while (pos + 4 <= end) {
    const int64_t bs = u32(data + pos);
    if (pos + 4 + bs > end) break;
    if (n >= max_records) { *resume = pos; return -2; }
    offs[n++] = pos;
    pos += 4 + bs;
  }
  *resume = pos;
  return n;
}

// Gather records (block_size word + body) in permuted order into `out`.
// rec_off points at record *bodies* (the u32 size word sits 4 bytes before).
// Returns total bytes written.
// Prefetch distance for the permuted gathers: the copies jump to random
// record offsets, so each memcpy begins with a cold miss unless the source
// lines are requested a few iterations ahead (~30% on a 1-core host).
static const int64_t kGatherAhead = 8;

int64_t hbam_gather_records(const uint8_t* data, const int64_t* rec_off,
                            const int64_t* rec_len, const int64_t* order,
                            int64_t n, uint8_t* out) {
  int64_t w = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (i + kGatherAhead < n) {
      const int64_t p = order ? order[i + kGatherAhead] : i + kGatherAhead;
      __builtin_prefetch(data + rec_off[p] - 4, 0, 0);
      __builtin_prefetch(data + rec_off[p] - 4 + 64, 0, 0);
    }
    const int64_t r = order ? order[i] : i;
    const int64_t len = rec_len[r] + 4;
    std::memcpy(out + w, data + rec_off[r] - 4, len);
    w += len;
  }
  return w;
}

// Chunked variant: records live in several separate buffers (one per file
// split), addressed by (chunk_id, rec_off).  Lets the sort pipeline write
// permuted parts without ever concatenating the per-split payloads into one
// host buffer — on a 1-core host that concat was the single largest cost.
int64_t hbam_gather_records_chunked(const uint8_t* const* chunks,
                                    const int32_t* chunk_id,
                                    const int64_t* rec_off,
                                    const int64_t* rec_len,
                                    const int64_t* order, int64_t n,
                                    uint8_t* out) {
  int64_t w = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (i + kGatherAhead < n) {
      const int64_t p = order ? order[i + kGatherAhead] : i + kGatherAhead;
      const uint8_t* src = chunks[chunk_id[p]] + rec_off[p] - 4;
      __builtin_prefetch(src, 0, 0);
      __builtin_prefetch(src + 64, 0, 0);
    }
    const int64_t r = order ? order[i] : i;
    const int64_t len = rec_len[r] + 4;
    std::memcpy(out + w, chunks[chunk_id[r]] + rec_off[r] - 4, len);
    w += len;
  }
  return w;
}

// Ragged byte rows → 0-padded [n, width] matrix (the text tokenizers' SoA
// builder: FASTQ/QSEQ seq+qual lines).  One memcpy + memset per row,
// threaded; ~memory bandwidth instead of NumPy's fancy-index gather.
void hbam_gather_rows(const uint8_t* data, const int64_t* starts,
                      const int64_t* lens, int64_t n, int64_t width,
                      uint8_t* out, int threads) {
  run_parallel(n, threads, [&](int64_t i) {
    uint8_t* row = out + i * width;
    int64_t len = lens[i] < width ? lens[i] : width;
    if (len < 0) len = 0;  // negative length must never become a size_t
    std::memcpy(row, data + starts[i], len);
    if (len < width) std::memset(row + len, 0, width - len);
  });
}

int hbam_abi_version() { return 5; }

}  // extern "C"
