"""The name-collation engine: one device primitive, a family of
biobambam-class workloads (ROADMAP item 3).

The dedup subsystem proved the shape — 64-bit murmur3 read-name hashes
collated with one ``lax.sort`` pass, content tie-breaks making every
decision input-order-free.  This package generalizes that pass into a
standalone primitive (:mod:`.device`) and builds three workloads on it,
all sharing the existing residency and part-write path:

- **Queryname sort** — ``sort -n`` / ``pipeline.sort_bam(...,
  sort_order="queryname")``: the chip groups records by name hash, the
  host ranks the (verified-distinct) bucket representative names with
  the exact samtools ``strnum_cmp`` natural comparator (:mod:`.host`),
  and one ``lexsort`` with the flag → position → index tie-breaks
  finishes.  ``SO:queryname`` is stamped in the output header.
- **Fixmate** — ``pipeline.fixmate_bam`` / the ``fixmate`` subcommand:
  mate coordinates, mate-unmapped/reverse flags, TLEN (the samtools
  5′-to-5′ rule) and MC mate-CIGAR tags filled from collated pairs
  (:mod:`.fixmate`), patched into a fresh gathered stream at write time
  — source payloads never mutate, the markdup flag-patch stance.
- **Markdup on unsorted input** — :mod:`dedup.device` pass 1 now *is*
  this engine's core (``collate_core``), so duplicate marking accepts
  queryname-grouped or shuffled input and elects identical winners;
  :mod:`dedup.oracle` remains the record-identical verification.

Collation is collision-safe, not collision-oblivious: hash buckets are
verified against actual name bytes on the host before any decision
trusts them (:func:`.host.verify_and_repair`), and the independent
oracles (:mod:`.oracle`) group by real names only.
"""

from .device import Collation, collate_by_name, collate_core
from .fixmate import (
    FIXMATE_FIELDS,
    FixmateEdits,
    apply_fixmate,
    compute_fixmate_edits,
)
from .host import (
    QuerynameStats,
    collation_counts,
    global_name_ranks,
    group_representatives,
    natural_compare,
    natural_sort_key,
    queryname_perm,
    verify_and_repair,
)
from .oracle import (
    collate_oracle,
    fixmate_oracle,
    mc_tag_of,
    queryname_sort_oracle,
)
from .signature import (
    COLLATE_EXTRA_FIELDS,
    QNAME_SEED2,
    collation_columns,
    concat_collation,
    name_hash_pair,
)

__all__ = [
    "COLLATE_EXTRA_FIELDS",
    "Collation",
    "FIXMATE_FIELDS",
    "FixmateEdits",
    "QNAME_SEED2",
    "QuerynameStats",
    "apply_fixmate",
    "collate_by_name",
    "collate_core",
    "collate_oracle",
    "collation_columns",
    "collation_counts",
    "compute_fixmate_edits",
    "concat_collation",
    "fixmate_oracle",
    "global_name_ranks",
    "group_representatives",
    "mc_tag_of",
    "name_hash_pair",
    "natural_compare",
    "natural_sort_key",
    "queryname_perm",
    "queryname_sort_oracle",
    "verify_and_repair",
]
