"""Fixmate: fill mate coordinates, mate flags, TLEN and MC tags from
collated pairs.

samtools-fixmate-class semantics (bam_mate.c), computed over the
engine's collation instead of requiring name-grouped input:

- **Pairing** — primary paired records (not secondary/supplementary;
  unmapped included) collate by the 64-bit name hash; exactly two
  candidates under one verified name are mates.  Orphans (no mate in
  the input) and singletons pass through untouched.
- **Mate fields** — each mate's ``next_refid``/``next_pos`` become the
  other's (post-placement) ``refid``/``pos``; ``FLAG_MATE_UNMAPPED``
  and ``FLAG_MATE_REVERSE`` are set *and cleared* from the mate's
  actual flags.
- **Placement** — an unmapped read with a mapped mate adopts the mate's
  ``refid``/``pos`` (and a recomputed single-base ``bin``) so the pair
  travels together, as samtools does before its mate sync.
- **TLEN** — the samtools 5′-to-5′ rule: ``own5 = endpos if reverse
  else pos`` (``endpos = pos + max(ref_span, 1)``); each mate gets
  ``mate5 - own5`` when both are mapped to the same reference, else 0.
- **MC** — the mate's CIGAR string as an ``MC:Z`` tag when the mate is
  mapped with a non-empty CIGAR; an existing MC tag is spliced out
  first, so re-running fixmate is byte-idempotent.

The decision pass is vectorized over the job-global collation columns;
records are rewritten only at write time, per part, into a fresh
gathered stream (:func:`io.bam.rebuild_record_stream`) — source
payloads never mutate, the markdup flag-patch stance.

Deviations from samtools (documented in the README): proper-pair (0x2)
recomputation and the ``-m`` mate-score (``ms``) tag are not
implemented.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from ..spec.bam import (
    CIGAR_OPS,
    FLAG_MATE_REVERSE,
    FLAG_MATE_UNMAPPED,
    FLAG_REVERSE,
    FLAG_UNMAPPED,
)
from ..utils.tracing import METRICS, span
from .device import Collation
from .host import collation_counts

#: The SoA fields a fixmate read needs (pass A computes columns from
#: them; pass B's tag splice recomputes the tag-region offset).
FIXMATE_FIELDS = (
    "refid", "pos", "flag", "rec_off", "rec_len",
    "l_read_name", "n_cigar_op", "l_seq",
)


@dataclass
class FixmateEdits:
    """Read-order edit plan over the whole job (row == global record
    index).  Field arrays are valid where ``mask``; ``place`` marks the
    unmapped-placed subset whose ``refid``/``pos``/``bin`` also change.
    ``mc_*`` address the packed MC-tag blob (len 0 = no tag)."""

    mask: np.ndarray  # bool[N]
    place: np.ndarray  # bool[N]
    flag: np.ndarray  # int32[N]
    refid: np.ndarray
    pos: np.ndarray
    bin: np.ndarray
    next_refid: np.ndarray
    next_pos: np.ndarray
    tlen: np.ndarray
    mc: np.ndarray  # uint8 blob
    mc_off: np.ndarray  # int64[N]
    mc_len: np.ndarray  # int32[N]
    counts: Dict[str, int]

    @property
    def n(self) -> int:
        return len(self.mask)


def _cigar_string(cigs: np.ndarray, off: int, n_ops: int) -> str:
    u32 = cigs[off : off + 4 * n_ops].view("<u4")
    return "".join(
        f"{int(c) >> 4}{CIGAR_OPS[int(c) & 0xF]}" for c in u32
    )


def compute_fixmate_edits(
    cols: Dict[str, np.ndarray], col: Collation
) -> FixmateEdits:
    """The vectorized decision pass: one edit plan from the job-global
    collation columns and the verified mate index."""
    n = len(cols["flag"])
    if n == 0:
        z32 = np.empty(0, np.int32)
        return FixmateEdits(
            mask=np.empty(0, bool), place=np.empty(0, bool),
            flag=z32, refid=z32, pos=z32, bin=z32, next_refid=z32,
            next_pos=z32, tlen=z32, mc=np.empty(0, np.uint8),
            mc_off=np.empty(0, np.int64), mc_len=z32,
            counts={"pairs": 0, "singletons": 0, "orphans": 0},
        )
    flag = cols["flag"].astype(np.int32)
    refid = cols["refid"].astype(np.int32)
    pos = cols["pos"].astype(np.int32)
    span_c = cols["span"].astype(np.int32)
    m = col.mate
    rows = np.flatnonzero(m >= 0)
    mate = m[rows].astype(np.int64)
    unmapped = (flag & FLAG_UNMAPPED) != 0

    # Placement first (samtools order): an unmapped read with a mapped
    # mate adopts the mate's coordinates, and the subsequent mate sync
    # reads the *placed* values.
    place_rows = rows[unmapped[rows] & ~unmapped[mate]]
    p_refid = refid.copy()
    p_pos = pos.copy()
    p_refid[place_rows] = refid[m[place_rows]]
    p_pos[place_rows] = pos[m[place_rows]]

    new_flag = flag[rows] & ~(FLAG_MATE_UNMAPPED | FLAG_MATE_REVERSE)
    new_flag |= np.where(unmapped[mate], FLAG_MATE_UNMAPPED, 0)
    new_flag |= np.where(
        (flag[mate] & FLAG_REVERSE) != 0, FLAG_MATE_REVERSE, 0
    )

    # TLEN, the samtools 5'-to-5' rule (bam_mate.c): own5 is the
    # alignment end for reverse reads, the start otherwise.
    endpos = pos.astype(np.int64) + np.maximum(span_c, 1)
    own5 = np.where((flag & FLAG_REVERSE) != 0, endpos, pos.astype(np.int64))
    both_mapped = (
        ~unmapped[rows]
        & ~unmapped[mate]
        & (refid[rows] == refid[mate])
        & (refid[rows] >= 0)
    )
    new_tlen = np.where(both_mapped, own5[mate] - own5[rows], 0)

    mask = np.zeros(n, dtype=bool)
    mask[rows] = True
    place = np.zeros(n, dtype=bool)
    place[place_rows] = True

    out_flag = flag.copy()
    out_flag[rows] = new_flag
    out_nrefid = np.zeros(n, np.int32)
    out_npos = np.zeros(n, np.int32)
    out_nrefid[rows] = p_refid[mate]
    out_npos[rows] = p_pos[mate]
    out_tlen = np.zeros(n, np.int32)
    out_tlen[rows] = new_tlen.astype(np.int32)
    # reg2bin(pos, pos+1) closed form for the single-base placed span.
    out_bin = np.where(
        p_pos >= 0, 4681 + (p_pos >> 14), 4680
    ).astype(np.int32)

    # MC tags: the mate's CIGAR string, for rows whose mate is mapped
    # with a non-empty CIGAR.  Ragged string formatting is the one
    # per-record host loop here (tag text is irreducibly ragged); it
    # runs over paired rows only.
    mc_off = np.zeros(n, dtype=np.int64)
    mc_len = np.zeros(n, dtype=np.int32)
    blob = bytearray()
    n_cig = cols["n_cig"].astype(np.int64)
    cig_off = cols["cig_off"].astype(np.int64)
    cigs = cols["cigs"]
    mc_rows = rows[~unmapped[mate] & (n_cig[mate] > 0)]
    for r, mt in zip(mc_rows, m[mc_rows]):
        tag = (
            b"MCZ"
            + _cigar_string(
                cigs, int(cig_off[mt]), int(n_cig[mt])
            ).encode()
            + b"\x00"
        )
        mc_off[r] = len(blob)
        mc_len[r] = len(tag)
        blob.extend(tag)

    counts = collation_counts(cols, col)
    METRICS.count("fixmate.records_updated", len(rows))
    METRICS.count("fixmate.placed_unmapped", len(place_rows))
    METRICS.count("fixmate.mc_tags", len(mc_rows))
    return FixmateEdits(
        mask=mask,
        place=place,
        flag=out_flag,
        refid=p_refid,
        pos=p_pos,
        bin=out_bin,
        next_refid=out_nrefid,
        next_pos=out_npos,
        tlen=out_tlen,
        mc=np.frombuffer(bytes(blob), dtype=np.uint8),
        mc_off=mc_off,
        mc_len=mc_len,
        counts=counts,
    )


_TAG_FIXED = {
    0x41: 1,  # A
    0x63: 1, 0x43: 1,  # c C
    0x73: 2, 0x53: 2,  # s S
    0x69: 4, 0x49: 4, 0x66: 4,  # i I f
}
_B_ELEM = {0x63: 1, 0x43: 1, 0x73: 2, 0x53: 2, 0x69: 4, 0x49: 4, 0x66: 4}


def find_tag_span(
    body: np.ndarray, tag_off: int, tag: bytes
) -> Optional[Tuple[int, int]]:
    """(offset, length) of a whole tag entry (tag+type+value) inside one
    record body, or None.  A malformed tag block stops the walk (the
    record keeps its bytes — fixmate never invents a splice)."""
    p = tag_off
    end = len(body)
    while p + 3 <= end:
        t0, t1, ty = int(body[p]), int(body[p + 1]), int(body[p + 2])
        q = p + 3
        if ty in _TAG_FIXED:
            q += _TAG_FIXED[ty]
        elif ty in (0x5A, 0x48):  # Z H: NUL-terminated
            while q < end and body[q] != 0:
                q += 1
            q += 1
        elif ty == 0x42:  # B: elem type + i32 count + payload
            if q + 5 > end:
                return None
            elem = _B_ELEM.get(int(body[q]))
            if elem is None:
                return None
            count = (
                int(body[q + 1])
                | (int(body[q + 2]) << 8)
                | (int(body[q + 3]) << 16)
                | (int(body[q + 4]) << 24)
            )
            q += 5 + elem * count
        else:
            return None
        if q > end:
            return None
        if bytes((t0, t1)) == tag:
            return p, q - p
        p = q
    return None


def apply_fixmate(batch, edits: FixmateEdits, row0: int):
    """Rewrite one split's records per the edit plan → a fresh
    :class:`io.bam.RecordBatch` (source payload untouched).

    MC splice offsets are found by a tag walk over the rows gaining an
    MC tag; the stream rebuild and every fixed-field patch are
    vectorized (:func:`io.bam.rebuild_record_stream`)."""
    from ..io.bam import RecordBatch, rebuild_record_stream

    k = batch.n_records
    soa = batch.soa
    rec_off = soa["rec_off"].astype(np.int64)
    rec_len = soa["rec_len"].astype(np.int64)
    sl = slice(row0, row0 + k)
    mask = edits.mask[sl]
    place = edits.place[sl]
    mc_len = edits.mc_len[sl].astype(np.int64)
    mc_off = edits.mc_off[sl]

    # Default: no splice (cut at end, zero length), no append.
    cut_off = rec_len.copy()
    cut_len = np.zeros(k, dtype=np.int64)
    tag_off = (
        32
        + soa["l_read_name"].astype(np.int64)
        + 4 * soa["n_cigar_op"].astype(np.int64)
        + (soa["l_seq"].astype(np.int64) + 1) // 2
        + soa["l_seq"].astype(np.int64)
    )
    with span("fixmate.stage.tag_walk", category="stage"):
        for i in np.flatnonzero(mc_len > 0):
            body = batch.data[rec_off[i] : rec_off[i] + rec_len[i]]
            hit = find_tag_span(body, int(tag_off[i]), b"MC")
            if hit is not None:
                cut_off[i], cut_len[i] = hit
    with span("fixmate.stage.apply", category="stage"):
        out, new_off, new_len = rebuild_record_stream(
            batch.data,
            rec_off,
            rec_len,
            cut_off,
            cut_len,
            edits.mc,
            mc_off,
            mc_len,
        )
        rows = np.flatnonzero(mask)
        if len(rows):
            body = new_off[rows]
            _poke_i32(out, body + 20, edits.next_refid[sl][rows])
            _poke_i32(out, body + 24, edits.next_pos[sl][rows])
            _poke_i32(out, body + 28, edits.tlen[sl][rows])
            _poke_u16(out, body + 14, edits.flag[sl][rows])
        p_rows = np.flatnonzero(place)
        if len(p_rows):
            body = new_off[p_rows]
            _poke_i32(out, body + 0, edits.refid[sl][p_rows])
            _poke_i32(out, body + 4, edits.pos[sl][p_rows])
            _poke_u16(out, body + 10, edits.bin[sl][p_rows])
    return RecordBatch(
        soa={"rec_off": new_off, "rec_len": new_len},
        data=out,
        keys=np.empty(0, np.int64),
    )


def _poke_i32(stream: np.ndarray, at: np.ndarray, vals: np.ndarray) -> None:
    v = vals.astype(np.int64) & 0xFFFFFFFF
    for b in range(4):
        stream[at + b] = ((v >> (8 * b)) & 0xFF).astype(np.uint8)


def _poke_u16(stream: np.ndarray, at: np.ndarray, vals: np.ndarray) -> None:
    v = vals.astype(np.int64) & 0xFFFF
    stream[at] = (v & 0xFF).astype(np.uint8)
    stream[at + 1] = ((v >> 8) & 0xFF).astype(np.uint8)
