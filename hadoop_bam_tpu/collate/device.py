"""The on-device name-collation primitive.

One ``lax.sort`` pass groups the whole record stream by its 64-bit
read-name hash, with *content* tie-breaks (candidate-first, then flag →
position → read index) so the collated order is a pure function of the
record multiset — shuffling the input cannot change any decision built
on top.  This is the generalization of the dedup subsystem's pass-1
pair collation (ROADMAP item 3): :mod:`dedup.device` now builds on the
same core, and queryname sort / fixmate / markdup-on-unsorted all share
it.

Everything is int32 (TPU-native lanes, no x64 dependence) and padded to
the next power of two by the public wrapper so only O(log N) program
shapes ever compile — the :mod:`dedup.device` stance, verbatim.

The core's outputs live in *collated* (sorted) space: the permutation,
segment ids over hash-equal runs of active rows, per-segment active and
candidate counts, and — for segments holding exactly two candidates —
the neighbor exchange index that makes the two mates see each other.
Hash buckets are only probabilistically name groups; every consumer
runs the host verification pass (:func:`collate.host.verify_buckets`)
over the actual name bytes before trusting a bucket.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_I32MAX = np.int32(2**31 - 1)


def _prev(a: jax.Array) -> jax.Array:
    """Row i-1's value at row i (row 0 sees itself; callers force the
    first boundary explicitly)."""
    return jnp.concatenate([a[:1], a[:-1]])


def collate_core(
    act: jax.Array,
    qh1: jax.Array,
    qh2: jax.Array,
    cand: jax.Array,
    tie1: jax.Array,
    tie2: jax.Array,
) -> Tuple[jax.Array, ...]:
    """The shared collation sort (call under jit; all int32[N]).

    Sort keys: ``(1-act, qh1, qh2, 1-cand, tie1, tie2, idx)`` — active
    rows first, grouped by the 64-bit hash, candidates leading their
    group, content tie-breaks, original index last for totality.

    Returns collated-space arrays ``(order, seg, size, csize, mated,
    nb)``: ``order`` (original index per collated row), ``seg``
    (hash-run segment id; inactive rows are singleton segments),
    ``size``/``csize`` (active / candidate rows in the row's segment),
    ``mated`` (bool: this row is one of a segment's exactly-2
    candidates), ``nb`` (the mate's collated-space row for mated rows;
    clipped self-ish elsewhere — gate every use on ``mated``).
    """
    n = act.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    zeros = jnp.zeros(n, jnp.int32)
    srt = lax.sort(
        (1 - act, qh1, qh2, 1 - cand, tie1, tie2, idx), num_keys=7
    )
    order = srt[6]
    acts = act[order]
    cands = cand[order]
    qh1s, qh2s = qh1[order], qh2[order]
    same = (
        (acts & _prev(acts)).astype(bool)
        & (qh1s == _prev(qh1s))
        & (qh2s == _prev(qh2s))
    )
    same = same.at[0].set(False)
    seg = jnp.cumsum(jnp.where(same, 0, 1)) - 1
    size = zeros.at[seg].add(acts)[seg]
    csize = zeros.at[seg].add(cands)[seg]
    # Candidates sort first within their segment, so the candidate rank
    # is the offset from the segment start; a 2-candidate segment's
    # mates sit at ranks 0 and 1 — adjacent rows.
    start = jnp.full(n, _I32MAX, jnp.int32).at[seg].min(idx)[seg]
    crank = idx - start
    mated = (cands == 1) & (csize == 2)
    nb = jnp.clip(jnp.where(crank == 0, idx + 1, idx - 1), 0, n - 1)
    return order, seg, size, csize, mated, nb


@jax.jit
def _collate_padded(act, qh1, qh2, cand, tie1, tie2):
    return collate_core(act, qh1, qh2, cand, tie1, tie2)


@dataclass
class Collation:
    """The host-side view of one collation pass.

    ``order``/``group`` cover the *active* rows only, in collated order:
    ``order[j]`` is the original index of collated row j and
    ``group[j]`` its dense hash-bucket id (buckets are contiguous runs).
    ``mate`` is read-order over all N rows: the mate's original index
    for rows collated into an exactly-two-candidate bucket, else -1.
    """

    order: np.ndarray  # int64[n_active]
    group: np.ndarray  # int32[n_active], dense 0..n_groups-1
    n_groups: int
    mate: np.ndarray  # int32[N] read order, -1 = no mate
    n_pairs: int

    def bucket_bounds(self) -> np.ndarray:
        """int64[n_groups+1] — collated-row bounds of each bucket."""
        if len(self.group) == 0:
            return np.zeros(1, dtype=np.int64)
        starts = np.flatnonzero(
            np.concatenate(([True], self.group[1:] != self.group[:-1]))
        )
        return np.concatenate((starts, [len(self.group)])).astype(np.int64)


def collate_by_name(
    cols: Dict[str, np.ndarray],
    active: Optional[np.ndarray] = None,
    candidates: Optional[np.ndarray] = None,
) -> Collation:
    """Run the device collation over read-order columns.

    ``cols`` needs ``qh1``/``qh2``/``flag``/``pos``.  ``active`` selects
    the rows to group (default: all); ``candidates`` the subset eligible
    for mate pairing (default: ``cols['cand']`` if present, else
    ``active``).  Rows are padded to the next power of two as inactive,
    so only O(log N) program shapes compile.
    """
    n = len(cols["qh1"])
    if n == 0:
        return Collation(
            order=np.empty(0, np.int64),
            group=np.empty(0, np.int32),
            n_groups=0,
            mate=np.empty(0, np.int32),
            n_pairs=0,
        )
    act = (
        np.ones(n, np.int32)
        if active is None
        else np.asarray(active, np.int32)
    )
    if candidates is None:
        cand = cols.get("cand")
        cand = act.copy() if cand is None else np.asarray(cand, np.int32)
    else:
        cand = np.asarray(candidates, np.int32)
    cand = cand & act  # a candidate outside the active set is meaningless
    padded = 1 << max(3, int(np.ceil(np.log2(n))))

    def pad(a, fill=0):
        out = np.full(padded, fill, dtype=np.int32)
        out[:n] = a
        return jnp.asarray(out)

    order_d, seg_d, _, _, mated_d, nb_d = _collate_padded(
        pad(act),
        pad(cols["qh1"]),
        pad(cols["qh2"]),
        pad(cand),
        pad(cols["flag"]),
        pad(cols["pos"]),
    )
    order = np.asarray(order_d, dtype=np.int64)
    seg = np.asarray(seg_d)
    mated = np.asarray(mated_d)
    nb = np.asarray(nb_d)

    # Active rows form the collated prefix… of the *active-sorted* order;
    # inactive real rows and padding interleave in the tail.  Mask by the
    # original activity column.
    act_rows = act[np.clip(order, 0, n - 1)].astype(bool) & (order < n)
    order_a = order[act_rows]
    seg_a = seg[act_rows]
    group = (
        np.cumsum(
            np.concatenate(([0], (seg_a[1:] != seg_a[:-1]).astype(np.int32)))
        )
        if len(seg_a)
        else np.empty(0, np.int64)
    ).astype(np.int32)
    mate = np.full(n, -1, dtype=np.int32)
    m_rows = np.flatnonzero(mated)
    if len(m_rows):
        mate[order[m_rows]] = order[nb[m_rows]]
    return Collation(
        order=order_a,
        group=group,
        n_groups=int(group[-1]) + 1 if len(group) else 0,
        mate=mate,
        n_pairs=len(m_rows) // 2,
    )
