"""Host-side collation columns: everything the name-collation engine
needs per decoded split, reduced to fixed-width int32 columns plus two
small ragged blobs (read names, raw CIGARs).

Same stance as :mod:`dedup.signature`: the host owns the ragged gathers
while the batch's sideband is still in hand; the chip owns the dense
collation passes downstream.  The 64-bit read-name hash pair defined
here (murmur3 seeds 0 and :data:`QNAME_SEED2`) is *the* collation key of
the whole engine — the dedup subsystem's signature columns reuse it, so
one definition serves markdup, queryname sort, and fixmate.

The name blob is retained because hash buckets are only probably name
groups: the engine's host verification pass
(:func:`collate.host.verify_buckets`) compares actual name bytes before
any decision trusts a bucket (64-bit collisions are ~never, but "~never"
is not a correctness argument).  The CIGAR blob feeds fixmate's MC
(mate-CIGAR) tags.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..ops.cigar import clip_spans_np
from ..spec.bam import (
    FLAG_PAIRED,
    FLAG_SECONDARY,
    FLAG_SUPPLEMENTARY,
)
from ..utils.murmur3 import murmurhash3_int32_batch

#: SoA columns the collation stages need beyond ``io.bam.SORT_FIELDS``.
COLLATE_EXTRA_FIELDS = ("l_read_name", "n_cigar_op", "l_seq")

#: Second murmur3 seed of the 64-bit read-name hash pair (seed 0 is the
#: first).  Shared with :mod:`dedup.signature` — the collation key must
#: be one definition across every workload built on it.
QNAME_SEED2 = 0x9747B28C

#: Ragged-blob column names rebased by :func:`concat_collation`.
_BLOB_COLS = (("name_off", "names"), ("cig_off", "cigs"))


def name_hash_pair(
    data: np.ndarray, soa: Dict
) -> Tuple[np.ndarray, np.ndarray]:
    """The 64-bit collation key: murmur3 of the qname bytes (sans the
    trailing NUL) under two seeds, as an (int32, int32) column pair."""
    name_off = soa["rec_off"].astype(np.int64) + 32
    name_len = np.maximum(soa["l_read_name"].astype(np.int64) - 1, 0)
    qh1 = murmurhash3_int32_batch(data, name_off, name_len, 0)
    qh2 = murmurhash3_int32_batch(data, name_off, name_len, QNAME_SEED2)
    return qh1, qh2


def ragged_slice(
    data: np.ndarray, offs: np.ndarray, lens: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Gather ragged ``data[offs[i] : offs[i]+lens[i]]`` slices into one
    packed blob; returns ``(blob, blob_offs)`` (``lens`` unchanged).  One
    fancy-index pass — no per-record Python loop."""
    lens = lens.astype(np.int64)
    total = int(lens.sum())
    out_off = np.cumsum(lens) - lens
    if total == 0:
        return np.empty(0, np.uint8), out_off
    idx = (
        np.repeat(offs.astype(np.int64) - out_off, lens)
        + np.arange(total, dtype=np.int64)
    )
    return np.asarray(data, dtype=np.uint8)[idx], out_off


def collation_columns(
    data: np.ndarray, soa: Dict, with_cigars: bool = False
) -> Dict[str, np.ndarray]:
    """Fixed-width collation columns for one decoded batch (original
    order), plus the packed name blob (and, for fixmate, the CIGAR blob).

    int32 columns: ``qh1``/``qh2`` (64-bit name hash), ``flag``,
    ``refid``, ``pos``, ``span`` (reference span from the CIGAR),
    ``cand`` (primary pairing candidate: paired and neither secondary
    nor supplementary — unmapped records *are* candidates here, unlike
    dedup's: fixmate must pair an unmapped mate), ``name_len``; int64
    ``name_off`` into the uint8 ``names`` blob.  ``with_cigars`` adds
    ``n_cig``/``cig_off`` and the raw little-endian-u32 ``cigs`` blob.
    """
    flag = soa["flag"].astype(np.int32)
    refid = soa["refid"].astype(np.int32)
    pos = soa["pos"].astype(np.int32)
    qh1, qh2 = name_hash_pair(data, soa)
    if with_cigars:
        # Reference spans feed fixmate's TLEN; the queryname path never
        # walks CIGARs (and its slim read omits the geometry columns).
        _, _, span = clip_spans_np(data, soa)
    else:
        span = np.zeros(len(flag), dtype=np.int64)
    cand = (
        ((flag & FLAG_PAIRED) != 0)
        & ((flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY)) == 0)
    ).astype(np.int32)
    name_src = soa["rec_off"].astype(np.int64) + 32
    name_len = np.maximum(
        soa["l_read_name"].astype(np.int64) - 1, 0
    ).astype(np.int32)
    names, name_off = ragged_slice(data, name_src, name_len)
    cols = {
        "qh1": qh1,
        "qh2": qh2,
        "flag": flag,
        "refid": refid,
        "pos": pos,
        "span": span.astype(np.int32),
        "cand": cand,
        "name_len": name_len,
        "name_off": name_off,
        "names": names,
    }
    if with_cigars:
        cig_src = (
            soa["rec_off"].astype(np.int64)
            + 32
            + soa["l_read_name"].astype(np.int64)
        )
        n_cig = soa["n_cigar_op"].astype(np.int32)
        cigs, cig_off = ragged_slice(data, cig_src, n_cig * 4)
        cols.update({"n_cig": n_cig, "cig_off": cig_off, "cigs": cigs})
    return cols


def concat_collation(
    parts: Sequence[Dict[str, np.ndarray]]
) -> Dict[str, np.ndarray]:
    """Concatenate per-split collation dicts into the job-global columns,
    rebasing the blob offsets into the concatenated blobs."""
    if not parts:
        return collation_columns(
            np.empty(0, np.uint8),
            {
                k: np.empty(0, np.int64)
                for k in (
                    "rec_off", "rec_len", "flag", "refid", "pos",
                    "l_read_name", "n_cigar_op",
                )
            },
        )
    if len(parts) == 1:
        return parts[0]
    out: Dict[str, np.ndarray] = {}
    for off_key, blob_key in _BLOB_COLS:
        if off_key not in parts[0]:
            continue
        base = np.cumsum(
            [0] + [len(p[blob_key]) for p in parts[:-1]]
        ).astype(np.int64)
        out[off_key] = np.concatenate(
            [p[off_key] + base[i] for i, p in enumerate(parts)]
        )
        out[blob_key] = np.concatenate([p[blob_key] for p in parts])
    for k in parts[0]:
        if k not in out:
            out[k] = np.concatenate([p[k] for p in parts])
    return out
