"""Pure-host oracles for the collation workloads.

Deliberately independent implementations — per-record Python walks,
dict-based grouping by the *actual* read name, no shared code with the
vectorized columns or the device collation — so the engine has real
oracles to be record-for-record identical to (the :mod:`dedup.oracle`
stance).  The one shared piece is the natural-order comparator itself
(:func:`collate.host.natural_compare`): it is spec-level, like murmur3
is for the dedup oracle.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

from ..spec import bam
from .host import natural_sort_key


def _primary_candidate(rec: bam.BamRecord) -> bool:
    return bool(rec.flag & bam.FLAG_PAIRED) and not rec.flag & (
        bam.FLAG_SECONDARY | bam.FLAG_SUPPLEMENTARY
    )


def collate_oracle(
    records: Sequence[bam.BamRecord],
) -> Tuple[Dict[str, List[int]], Dict[int, int]]:
    """(name → record indices, record index → mate index) by exact-name
    grouping; a mate exists iff a name has exactly two primary paired
    candidates."""
    groups: Dict[str, List[int]] = defaultdict(list)
    for i, r in enumerate(records):
        groups[r.read_name].append(i)
    mates: Dict[int, int] = {}
    for idxs in groups.values():
        cands = [i for i in idxs if _primary_candidate(records[i])]
        if len(cands) == 2:
            mates[cands[0]], mates[cands[1]] = cands[1], cands[0]
    return dict(groups), mates


def queryname_sort_oracle(records: Sequence[bam.BamRecord]) -> List[int]:
    """Output order of the queryname sort: natural name order, then
    flag, then position, then input index (the engine's documented
    tie-break chain)."""
    names = [r.read_name.encode() for r in records]
    keyed = sorted(
        range(len(records)),
        key=lambda i: (
            natural_sort_key(names[i]),
            records[i].flag,
            records[i].pos,
            i,
        ),
    )
    return keyed


def _endpos(rec: bam.BamRecord) -> int:
    span = sum(n for n, op in rec.cigar if op in "MDN=X")
    return rec.pos + max(span, 1)


def fixmate_oracle(
    records: Sequence[bam.BamRecord],
) -> List[Dict[str, object]]:
    """Expected post-fixmate field values per record (input order):
    ``flag``, ``refid``, ``pos``, ``next_refid``, ``next_pos``,
    ``tlen``, and ``mc`` (the MC:Z string, or None).  Non-mated records
    keep their input values with ``mc`` None (untouched)."""
    _, mates = collate_oracle(records)
    out: List[Dict[str, object]] = []
    for i, r in enumerate(records):
        exp = {
            "flag": r.flag,
            "refid": r.refid,
            "pos": r.pos,
            "next_refid": r.next_refid,
            "next_pos": r.next_pos,
            "tlen": r.tlen,
            "mc": None,
        }
        j = mates.get(i)
        if j is None:
            out.append(exp)
            continue
        mt = records[j]
        my_unmapped = bool(r.flag & bam.FLAG_UNMAPPED)
        mt_unmapped = bool(mt.flag & bam.FLAG_UNMAPPED)
        # Placement before the mate sync, the samtools order.
        my_refid, my_pos = r.refid, r.pos
        mt_refid, mt_pos = mt.refid, mt.pos
        if my_unmapped and not mt_unmapped:
            my_refid, my_pos = mt.refid, mt.pos
        if mt_unmapped and not my_unmapped:
            mt_refid, mt_pos = r.refid, r.pos
        flag = r.flag & ~(bam.FLAG_MATE_UNMAPPED | bam.FLAG_MATE_REVERSE)
        if mt_unmapped:
            flag |= bam.FLAG_MATE_UNMAPPED
        if mt.flag & bam.FLAG_REVERSE:
            flag |= bam.FLAG_MATE_REVERSE
        tlen = 0
        if (
            not my_unmapped
            and not mt_unmapped
            and r.refid == mt.refid
            and r.refid >= 0
        ):
            own5 = _endpos(r) if r.flag & bam.FLAG_REVERSE else r.pos
            mate5 = _endpos(mt) if mt.flag & bam.FLAG_REVERSE else mt.pos
            tlen = mate5 - own5
        mc: Optional[str] = None
        if not mt_unmapped and mt.n_cigar_op > 0:
            mc = mt.cigar_string()
        exp.update(
            {
                "flag": flag,
                "refid": my_refid,
                "pos": my_pos,
                "next_refid": mt_refid,
                "next_pos": mt_pos,
                "tlen": tlen,
                "mc": mc,
            }
        )
        out.append(exp)
    return out


def mc_tag_of(rec: bam.BamRecord) -> Optional[str]:
    """The record's MC:Z tag value, by an independent per-record tag
    walk (the test-side reader for the fixmate field comparison)."""
    raw = rec.tags_raw
    p = 0
    while p + 3 <= len(raw):
        tag = raw[p : p + 2]
        ty = raw[p + 2 : p + 3]
        q = p + 3
        if ty in b"AcC":
            q += 1
        elif ty in b"sS":
            q += 2
        elif ty in b"iIf":
            q += 4
        elif ty in b"ZH":
            e = raw.index(b"\x00", q)
            if tag == b"MC" and ty == b"Z":
                return raw[q:e].decode()
            q = e + 1
            p = q
            continue
        elif ty == b"B":
            elem = raw[q : q + 1]
            import struct

            (count,) = struct.unpack_from("<I", raw, q + 1)
            size = {b"c": 1, b"C": 1, b"s": 2, b"S": 2,
                    b"i": 4, b"I": 4, b"f": 4}[elem]
            q += 5 + size * count
        else:
            return None
        p = q
    return None
