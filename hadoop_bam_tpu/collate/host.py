"""Host finishing passes over a device collation.

The chip groups by the 64-bit name hash (:mod:`collate.device`); the
host owns what tensors cannot express cheaply:

- **Bucket verification** — hash buckets are only *probably* name
  groups.  One vectorized adjacent-row byte compare over the collated
  order proves every bucket name-homogeneous; the rare failing bucket
  (a 64-bit collision, or a test forcing one) is repaired by an exact
  regroup over its actual name bytes, and any mate pairing the hash
  faked is re-derived from real names.  No decision downstream ever
  rests on hash equality alone.
- **The samtools natural-order comparator** — ``strnum_cmp``
  (bam_sort.c) reproduced exactly, digit-run-by-digit-run, including
  its leading-zero tie rule.  Queryname output order sorts the (few,
  verified-distinct) bucket representative names with it; records never
  pass through a per-record Python comparison.
- **The queryname permutation** — bucket rank from the comparator, then
  one ``np.lexsort`` with the engine's content tie-breaks (flag →
  position → read index), so the output is a pure function of the
  record multiset (the shuffled-input test's contract).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, Tuple

import numpy as np

from ..spec.bam import FLAG_PAIRED
from ..utils.tracing import METRICS, span
from .device import Collation, collate_by_name


def natural_compare(a: bytes, b: bytes) -> int:
    """samtools ``strnum_cmp`` (bam_sort.c), bit-for-bit: runs of digits
    compare numerically (leading zeros skipped; equal values with
    different zero counts order by consumed length — more zeros first),
    everything else by byte value.  Returns <0, 0, >0."""
    i = j = 0
    la, lb = len(a), len(b)
    while i < la and j < lb:
        ca, cb = a[i], b[j]
        da, db = 0x30 <= ca <= 0x39, 0x30 <= cb <= 0x39
        if da and db:
            while i < la and a[i] == 0x30:
                i += 1
            while j < lb and b[j] == 0x30:
                j += 1
            while (
                i < la and j < lb
                and 0x30 <= a[i] <= 0x39 and 0x30 <= b[j] <= 0x39
                and a[i] == b[j]
            ):
                i += 1
                j += 1
            da = i < la and 0x30 <= a[i] <= 0x39
            db = j < lb and 0x30 <= b[j] <= 0x39
            if da and db:
                k = 0
                while (
                    i + k < la and j + k < lb
                    and 0x30 <= a[i + k] <= 0x39
                    and 0x30 <= b[j + k] <= 0x39
                ):
                    k += 1
                if i + k < la and 0x30 <= a[i + k] <= 0x39:
                    return 1
                if j + k < lb and 0x30 <= b[j + k] <= 0x39:
                    return -1
                return int(a[i]) - int(b[j])
            if da:
                return 1
            if db:
                return -1
            if i != j:
                return 1 if i < j else -1
        else:
            if ca != cb:
                return int(ca) - int(cb)
            i += 1
            j += 1
    if i < la:
        return 1
    if j < lb:
        return -1
    return 0


natural_sort_key = functools.cmp_to_key(natural_compare)


def _name_bytes(cols: Dict[str, np.ndarray], row: int) -> bytes:
    o = int(cols["name_off"][row])
    return cols["names"][o : o + int(cols["name_len"][row])].tobytes()


def _adjacent_equal_mask(
    cols: Dict[str, np.ndarray], left: np.ndarray, right: np.ndarray
) -> np.ndarray:
    """bool per (left, right) row pair: identical name bytes.  Fully
    vectorized — one ragged gather per side, one ``minimum.reduceat``."""
    ll = cols["name_len"][left].astype(np.int64)
    lr = cols["name_len"][right].astype(np.int64)
    eq = ll == lr
    rows = np.flatnonzero(eq & (ll > 0))
    if len(rows) == 0:
        return eq
    lens = ll[rows]
    starts = np.cumsum(lens) - lens
    total = int(lens.sum())
    within = np.arange(total, dtype=np.int64) - np.repeat(starts, lens)
    li = np.repeat(cols["name_off"][left[rows]], lens) + within
    ri = np.repeat(cols["name_off"][right[rows]], lens) + within
    match = (cols["names"][li] == cols["names"][ri]).astype(np.int8)
    eq[rows] = np.minimum.reduceat(match, starts).astype(bool)
    return eq


def verify_and_repair(
    col: Collation, cols: Dict[str, np.ndarray]
) -> Tuple[Collation, int]:
    """Prove every hash bucket name-homogeneous; exactly regroup (and
    re-pair) the ones that aren't.  Returns the verified collation and
    the number of buckets that held a hash collision (also counted as
    ``collate.hash_collisions``)."""
    n_act = len(col.order)
    if n_act == 0:
        return col, 0
    same_group = np.concatenate(
        ([False], col.group[1:] == col.group[:-1])
    )
    pairs = np.flatnonzero(same_group)
    ok = np.ones(n_act, dtype=bool)
    if len(pairs):
        ok[pairs] = _adjacent_equal_mask(
            cols, col.order[pairs - 1], col.order[pairs]
        )
    bad_rows = np.flatnonzero(~ok)
    if len(bad_rows) == 0:
        return col, 0
    bad_groups = np.unique(col.group[bad_rows])
    bounds = col.bucket_bounds()
    order = col.order.copy()
    mate = col.mate.copy()
    # Subgroup tag per collated row: 0 everywhere except repaired
    # buckets, where distinct names get distinct tags — the dense
    # renumber below then splits exactly those buckets.
    subtag = np.zeros(n_act, dtype=np.int64)
    for g in bad_groups:
        b0, b1 = int(bounds[g]), int(bounds[g + 1])
        rows = order[b0:b1]
        by_name: Dict[bytes, list] = {}
        for r in rows:
            by_name.setdefault(_name_bytes(cols, int(r)), []).append(int(r))
        # Deterministic sub-bucket order: by name bytes (the rank pass
        # re-orders buckets anyway; this only has to be content-only).
        new_rows = []
        for t, name in enumerate(sorted(by_name)):
            members = by_name[name]
            new_rows.extend(members)
            subtag[b0 + len(new_rows) - len(members) : b0 + len(new_rows)] = t
            # Re-derive the mate pairing the hash faked: exactly two
            # candidates sharing the *actual* name are mates.
            cands = [r for r in members if cols["cand"][r]]
            for r in members:
                mate[r] = -1
            if len(cands) == 2:
                mate[cands[0]], mate[cands[1]] = cands[1], cands[0]
        order[b0:b1] = new_rows
    boundary = np.concatenate(
        (
            [True],
            (col.group[1:] != col.group[:-1])
            | (subtag[1:] != subtag[:-1]),
        )
    )
    group = (np.cumsum(boundary) - 1).astype(np.int32)
    n_coll = int(len(bad_groups))
    METRICS.count("collate.hash_collisions", n_coll)
    return (
        Collation(
            order=order,
            group=group,
            n_groups=int(group[-1]) + 1,
            mate=mate,
            n_pairs=int((mate >= 0).sum()) // 2,
        ),
        n_coll,
    )


@dataclass
class QuerynameStats:
    n_records: int
    n_groups: int
    n_collisions: int


def queryname_perm(
    cols: Dict[str, np.ndarray]
) -> Tuple[np.ndarray, QuerynameStats]:
    """The queryname-sort output permutation (int64[N], read-order
    indices in output order): samtools natural name order, then the
    engine's content tie-breaks (flag → position → read index).

    The chip collates by hash; the host sorts only the *bucket
    representatives* (verified distinct names — one comparator call per
    bucket pair, never per record) and one ``lexsort`` finishes."""
    n = len(cols["qh1"])
    if n == 0:
        return np.empty(0, np.int64), QuerynameStats(0, 0, 0)
    with span("collate.stage.device", category="stage"):
        col = collate_by_name(cols, candidates=np.zeros(n, np.int32))
    with span("collate.stage.verify", category="stage"):
        col, n_coll = verify_and_repair(col, cols)
    with span("collate.stage.rank", category="stage"):
        bounds = col.bucket_bounds()
        reps = [
            _name_bytes(cols, int(col.order[int(bounds[g])]))
            for g in range(col.n_groups)
        ]
        by_name = sorted(
            range(col.n_groups), key=lambda g: natural_sort_key(reps[g])
        )
        rank_of_group = np.empty(col.n_groups, dtype=np.int64)
        rank_of_group[by_name] = np.arange(col.n_groups, dtype=np.int64)
        grank = np.empty(n, dtype=np.int64)
        grank[col.order] = rank_of_group[col.group]
        perm = np.lexsort(
            (
                cols["pos"].astype(np.int64),
                cols["flag"].astype(np.int64),
                grank,
            )
        ).astype(np.int64)
    METRICS.count("collate.groups", col.n_groups)
    return perm, QuerynameStats(n, col.n_groups, n_coll)


def group_representatives(
    cols: Dict[str, np.ndarray], col: Collation
) -> list:
    """One representative name (bytes) per verified bucket, indexed by
    group id.  After :func:`verify_and_repair` every bucket is
    name-homogeneous, so the first collated row speaks for the group —
    this is the per-host half of the distributed rank pass: hosts
    allgather only these representatives (one short name per *group*,
    not per record) and rank the union with the natural comparator."""
    bounds = col.bucket_bounds()
    return [
        _name_bytes(cols, int(col.order[int(bounds[g])]))
        for g in range(col.n_groups)
    ]


def global_name_ranks(rep_lists) -> Dict[bytes, int]:
    """Fold per-host representative lists into one dense global rank
    table: the union of distinct names in samtools natural order.  Every
    host computes this over the same allgathered lists, so ranks agree
    mesh-wide without a coordinator.  Cross-host hash collisions cost
    nothing here — ranking keys on actual name bytes, two hosts whose
    *different* names share a 64-bit hash simply get two ranks."""
    union = set()
    for reps in rep_lists:
        union.update(reps)
    ordered = sorted(union, key=natural_sort_key)
    return {name: r for r, name in enumerate(ordered)}


def collation_counts(
    cols: Dict[str, np.ndarray], col: Collation
) -> Dict[str, int]:
    """The engine's census: ``pairs`` (mated primary pairs),
    ``singletons`` (records that never pair — FLAG_PAIRED unset),
    ``orphans`` (pairing candidates whose mate never collated: absent
    mate, or an anomalous >2-candidate name).  Counted into the
    ``collate.*`` METRICS namespace."""
    counts = {
        "pairs": col.n_pairs,
        "singletons": int(((cols["flag"] & FLAG_PAIRED) == 0).sum()),
        "orphans": int(((cols["cand"] == 1) & (col.mate < 0)).sum()),
    }
    for k, v in counts.items():
        METRICS.count(f"collate.{k}", v)
    return counts
