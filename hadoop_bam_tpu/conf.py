"""Configuration: the Hadoop `Configuration` equivalent.

The reference's entire flag system is Hadoop string properties in the
``hadoopbam.*`` / ``hbam.*`` namespaces (SURVEY.md §5 key inventory; e.g.
reference BAMInputFormat.java:89-111, AnySAMInputFormat.java:60-62,
FormatConstants.java:57-58).  This module reproduces that contract: a string
key/value map with lenient boolean parsing (reference util/ConfHelper.java:41-69)
plus typed helpers, so user code can drive the TPU backend with the same
property names it used against Hadoop-BAM.
"""

from __future__ import annotations

from typing import Iterator, Mapping, Optional


# Complete property-name inventory, mirrored from the reference (SURVEY.md §5).
BAM_BOUNDED_TRAVERSAL = "hadoopbam.bam.bounded-traversal"
BAM_ENABLE_BAI_SPLITTER = "hadoopbam.bam.enable-bai-splitter"
BAM_INTERVALS = "hadoopbam.bam.intervals"
BAM_TRAVERSE_UNPLACED_UNMAPPED = "hadoopbam.bam.traverse-unplaced-unmapped"
BAM_WRITE_SPLITTING_BAI = "hadoopbam.bam.write-splitting-bai"
# Fuse samtools-class duplicate marking into the coordinate sort (the
# dedup/ subsystem): duplicates get FLAG_DUPLICATE (0x400) ORed into
# their written flag bytes.  Equivalent to sort_bam(mark_duplicates=True).
BAM_MARK_DUPLICATES = "hadoopbam.bam.mark-duplicates"
# Output ordering of pipeline.sort_bam: "coordinate" (default) or
# "queryname" (the collation engine's samtools-natural-order name sort,
# the CLI's `sort -n`).  The output header's @HD SO: field reports
# whichever was actually used.
BAM_SORT_ORDER = "hadoopbam.bam.sort-order"
ANYSAM_TRUST_EXTS = "hadoopbam.anysam.trust-exts"
ANYSAM_OUTPUT_FORMAT = "hadoopbam.anysam.output-format"
ANYSAM_WRITE_HEADER = "hadoopbam.anysam.write-header"
CRAM_REFERENCE_SOURCE_PATH = "hadoopbam.cram.reference-source-path"
SAMHEADERREADER_VALIDATION_STRINGENCY = (
    "hadoopbam.samheaderreader.validation-stringency"
)
VCFRECORDREADER_VALIDATION_STRINGENCY = (
    "hadoopbam.vcfrecordreader.validation-stringency"
)
VCF_TRUST_EXTS = "hadoopbam.vcf.trust-exts"
VCF_INTERVALS = "hadoopbam.vcf.intervals"
VCF_OUTPUT_FORMAT = "hadoopbam.vcf.output-format"
VCF_WRITE_HEADER = "hadoopbam.vcf.write-header"
FASTQ_BASE_QUALITY_ENCODING = "hbam.fastq-input.base-quality-encoding"
FASTQ_FILTER_FAILED_QC = "hbam.fastq-input.filter-failed-qc"
QSEQ_BASE_QUALITY_ENCODING = "hbam.qseq-input.base-quality-encoding"
QSEQ_FILTER_FAILED_QC = "hbam.qseq-input.filter-failed-qc"
INPUT_BASE_QUALITY_ENCODING = "hbam.input.base-quality-encoding"
INPUT_FILTER_FAILED_QC = "hbam.input.filter-failed-qc"
FASTQ_OUTPUT_BASE_QUALITY_ENCODING = "hbam.fastq-output.base-quality-encoding"
QSEQ_OUTPUT_BASE_QUALITY_ENCODING = "hbam.qseq-output.base-quality-encoding"
# FASTQ ingest plane (ingest.py): decoded payloads are re-chunked into
# claim regions of this many bytes for the record-boundary scan kernel
# (default 57088, the device inflate payload), each scanned with this
# much overlap past the claim so the tail record can complete (default
# 2048).  device-scan: "true" forces the Pallas record-scan tier on,
# "false" off; unset defers to the inflate-lanes auto rule.
INGEST_CHUNK_BYTES = "hadoopbam.ingest.chunk-bytes"
INGEST_SCAN_OVERLAP = "hadoopbam.ingest.scan-overlap"
INGEST_DEVICE_SCAN = "hadoopbam.ingest.device-scan"
# New in the TPU build (per driver BASELINE.json north star).
BACKEND = "hadoopbam.backend"
# Lockstep-lane Pallas inflate tier (ops/pallas/inflate_lanes.py): "true"
# forces it on, "false" off; unset defers to the local-latency auto rule
# (on for real, local accelerators — see ops.flate.lanes_tier_enabled).
INFLATE_LANES = "hadoopbam.inflate.lanes"
# Lockstep-lane Pallas deflate tier (ops/pallas/deflate_lanes.py): the
# LZ77 match-finding device encoder behind bgzf_compress_device and the
# part-write path.  Same semantics: "true"/"false" force, unset defers to
# the local-latency auto rule (ops.flate.deflate_lanes_tier_enabled).
DEFLATE_LANES = "hadoopbam.deflate.lanes"
# Device-resident part writes (ops/pallas/gather_stream.py + crc32.py):
# the sorted record gather, markdup flag patch and per-member CRC32 all
# run on chip over the HBM-resident split payloads, feeding the deflate
# lanes device-to-device so only compressed bytes come back d2h.  Same
# semantics as the codec tiers: "true"/"false" force, unset defers to the
# local-latency auto rule (ops.flate.device_write_enabled); parts whose
# batch lacks residency tier down to the host gather per part.
WRITE_DEVICE = "hadoopbam.write.device"
# Lockstep-lane Pallas rANS 4x8 tier (ops/pallas/rans_lanes.py): the
# device decoder for CRAM's entropy codec, the third codec family beside
# inflate/deflate.  Same semantics: "true"/"false" force, unset defers to
# the local-latency auto rule (ops.flate.rans_lanes_tier_enabled); slices
# that trip a size/VMEM/context/format gate tier down per-slice to the
# NumPy host decoder and the Python oracle (spec/cram_codecs.py).
CRAM_RANS_LANES = "hadoopbam.cram.rans-lanes"
# Device BCF record-chain walk (ops/pallas/bcf_chain.py): the variant
# plane's boundary walk + fixed-shared-column extraction on chip, BCF
# being the fourth codec-family client of the DeviceStream (BGZF framing
# rides the inflate lanes already).  Same semantics: "true"/"false"
# force, unset defers to the local-latency auto rule
# (ops.flate.bcf_chain_tier_enabled); windows that trip a framing or
# domain gate tier down per-window — never per-launch — to the bit-exact
# NumPy walk and then the spec/bcf.py per-record oracle.
BCF_CHAIN = "hadoopbam.bcf.chain"
# Split-read pipelining depth (pipeline._read_splits_pipelined /
# DeviceStream.read_splits): how many splits are in flight at once in the
# read-ahead pool — split k+1's file read + inflate (h2d upload + device
# kernels when the lanes tier is on) overlap split k's downstream
# processing.  Resolution order: explicit depth argument → this key → the
# HBAM_READ_DEPTH env var → 2.  The chosen depth is surfaced in the run
# manifest (modes.read_depth) so a round's overlap numbers carry their
# pipelining provenance.
READ_DEPTH = "hadoopbam.read.depth"
# The local-latency auto rule's RTT gate (milliseconds, default 5.0):
# every device tier (inflate/deflate lanes, device write, device parse)
# auto-declines when the host↔device round trip exceeds this.  A ≥2-deep
# DeviceStream pipeline keeps that many launches in flight, hiding
# per-launch RTT behind the other splits' compute, so the stream relaxes
# the effective gate to depth × this value (the pipelined-mode
# relaxation); setting the key higher lets a tunneled dev topology
# (~70 ms RTT) measure the built device path end-to-end instead of
# auto-declining every tier.  The default is unchanged from the
# pre-DeviceStream rule.
DEVICE_AUTO_RTT_MS = "hadoopbam.device.auto-rtt-ms"
# Resident service mode (serve/): a long-lived daemon owning the TPU,
# reached over a localhost/UDS socket with length-prefixed JSON framing.
# Either the UDS socket path or a 127.0.0.1 TCP port selects the
# transport (socket wins when both are set; neither → a per-user default
# socket under the temp dir).
SERVE_SOCKET = "hadoopbam.serve.socket"
SERVE_PORT = "hadoopbam.serve.port"
# Byte budgets for the daemon's warm state: the header/index cache
# (serve/cache.py LRU, keyed by (path, size, mtime) file identity) and
# the HBM residency arena (serve/arena.py — decoded split windows, with
# their device-resident payloads when the inflate tier left any, kept
# across requests instead of freed per job).
SERVE_CACHE_BYTES = "hadoopbam.serve.cache-bytes"
SERVE_ARENA_BYTES = "hadoopbam.serve.arena-bytes"
# Admission batch window (milliseconds): member-decompress work arriving
# within the window coalesces into one shared ≤128-lane launch
# (serve/batching.py); 0 disables coalescing (every request launches
# alone).
SERVE_BATCH_WINDOW_MS = "hadoopbam.serve.batch-window-ms"
# Max concurrently-running submitted jobs (sort submissions run in a
# bounded pool; view/flagstat answer inline per connection).
SERVE_MAX_INFLIGHT = "hadoopbam.serve.max-inflight"
# Admission control (serve/admission.py): the token-style concurrency
# budget shared by the data-plane ops (view=1, flagstat=2, sort=4 cost
# units; control-plane ops are never gated), the admission queue's depth
# bound (crossing it sheds with code SHED + a retry_after_ms hint), and
# the queue-wait p95 bound in milliseconds (crossing it sheds with code
# RETRY_AFTER; 0 disables the wait rule, depth still bounds).
SERVE_ADMISSION_TOKENS = "hadoopbam.serve.admission-tokens"
SERVE_MAX_QUEUE = "hadoopbam.serve.max-queue"
SERVE_MAX_QUEUE_MS = "hadoopbam.serve.max-queue-ms"
# Crash-safe job journal path (serve/journal.py): append-only JSONL of
# job submissions + state transitions, fsync'd per append.  A restarted
# daemon pointed at the same journal reports accurate terminal states,
# resumes interrupted sorts through the spill-manifest/part checkpoints
# (byte-identical), and marks anything unresumable "lost" instead of
# forgetting it.  Unset = no journal (jobs die with the process).
SERVE_JOURNAL = "hadoopbam.serve.journal"
# Daemon flight recorder (serve/flightrec.py): a bounded on-disk JSONL
# ring of periodic metrics/gauge/ledger snapshots (queue depth, admission
# tokens, arena/cache/HBM occupancy, shed + OOM counters), written at the
# configured cadence and finalized on SIGTERM drain.  After a kill -9 the
# ring is replayable by the stdlib-only tools/flightrec_report.py, so the
# journal-driven restart can also *explain* what the daemon was doing in
# its final seconds.  FLIGHTREC is the ring's base path (two alternating
# segment files <base>.0/<base>.1 bound total size); unset = no recorder.
SERVE_FLIGHTREC = "hadoopbam.serve.flightrec"
SERVE_FLIGHTREC_CADENCE_MS = "hadoopbam.serve.flightrec-cadence-ms"
SERVE_FLIGHTREC_BYTES = "hadoopbam.serve.flightrec-bytes"
# Request-scoped tracing plane (PR 12).  REQUEST_TRACING ("true" by
# default) arms the daemon's timeline tracer and gives every request a
# Dapper-style RequestContext — a 128-bit trace id originated by the
# client (ServeClient) or at dispatch, carried through admission, the
# lane batcher, endpoints, the executor and the OOM/journal seams, and
# annotated onto every tracer event so one request's causal tree is
# reassemblable from the ring.  "false" turns the whole plane off
# (requests still work; they just leave no per-request trail).
SERVE_REQUEST_TRACING = "hadoopbam.serve.request-tracing"
# Tail sampler: a request slower than EXEMPLAR_THRESHOLD_MS (or ending
# in SHED/DEADLINE_EXCEEDED/error, or that OOM-tiered-down) gets its
# full event set copied out of the tracer ring into a bounded per-daemon
# exemplar store (EXEMPLARS_MAX entries, oldest evicted), exportable via
# the `exemplars` serve op; with EXEMPLAR_DIR set each exemplar is also
# spilled as <dir>/<trace_id>.json so it survives the daemon.
# Threshold 0 disables the latency trigger (outcome triggers stay).
SERVE_EXEMPLAR_THRESHOLD_MS = "hadoopbam.serve.exemplar-threshold-ms"
SERVE_EXEMPLARS_MAX = "hadoopbam.serve.exemplars-max"
SERVE_EXEMPLAR_DIR = "hadoopbam.serve.exemplar-dir"
# JSONL access log: one structured line per completed request (trace id,
# op, outcome, duration, queue/batch waits, tier decisions, shed/OOM
# flags) at the given base path, rotated with the flight recorder's
# two-segment scheme under ACCESS_LOG_BYTES total; joins with exemplars
# on trace id.  Unset = no access log.
SERVE_ACCESS_LOG = "hadoopbam.serve.access-log"
SERVE_ACCESS_LOG_BYTES = "hadoopbam.serve.access-log-bytes"
# SLO monitor (serve/slo.py): declared objectives per op, e.g.
# "view:latency=100@0.999;sort:availability=0.99" (latency thresholds in
# ms; targets as fractions), evaluated over two sliding windows
# ("fast_s,slow_s" seconds, default "60,600") from the existing per-op
# histograms.  Multi-window burn-rate alerts surface in the stats op,
# the flight recorder, and the Prometheus text.  Unset = the default
# objective set (serve/slo.py DEFAULT_OBJECTIVES).
SERVE_SLO = "hadoopbam.serve.slo"
SERVE_SLO_WINDOWS = "hadoopbam.serve.slo-windows"
# Pre-compile the pow2 geometry buckets of the device kernels at daemon
# startup (serve/warmup.py) so first-request latency is warm; "false"
# skips the warm-up (first requests then pay the compiles).
SERVE_WARMUP = "hadoopbam.serve.warmup"
# Fleet topology (PR 18, serve/fleet.py + serve/router.py): with
# FLEET_DIR set, each daemon publishes an atomic member record (name,
# endpoint, journal path, flight-recorder base) there and refreshes it
# every FLEET_HEARTBEAT_MS; the front router builds its consistent-hash
# ring from those records, declares a member dead after
# FLEET_HEARTBEAT_TIMEOUT_MS of silence (then consults the flight
# recorder before adopting its journal), and spreads ownership with
# FLEET_VNODES virtual nodes per member.  FLEET_NAME defaults to
# "daemon-<pid>".  Unset FLEET_DIR = the single-daemon topology,
# untouched.
FLEET_DIR = "hadoopbam.fleet.dir"
FLEET_NAME = "hadoopbam.fleet.member-name"
FLEET_HEARTBEAT_MS = "hadoopbam.fleet.heartbeat-ms"
FLEET_HEARTBEAT_TIMEOUT_MS = "hadoopbam.fleet.heartbeat-timeout-ms"
FLEET_VNODES = "hadoopbam.fleet.vnodes"
# The router's own endpoint (UDS path, or a 127.0.0.1 TCP port; default
# a per-user /tmp/hbam-fleet-<uid>.sock), and the federated admission
# sizing: FLEET_TOKENS cost-units in flight across the whole fleet,
# FLEET_FILE_TOKENS for any single routing key (the hot-file cap — one
# zipfian head must not starve every other file's owner).
FLEET_SOCKET = "hadoopbam.fleet.socket"
FLEET_PORT = "hadoopbam.fleet.port"
FLEET_TOKENS = "hadoopbam.fleet.tokens"
FLEET_FILE_TOKENS = "hadoopbam.fleet.file-tokens"
# "true" ships a draining member's warm arena windows to their new ring
# owners as PR 15 compressed BGZF members before the ring drops it, so
# a planned leave moves cache warmth instead of re-paying cold reads.
# Default "false" (a kill is never migrated — the corpse can't export).
FLEET_MIGRATE_WARMTH = "hadoopbam.fleet.migrate-warmth"
# Error-handling policy: "strict" (default — any corrupt BGZF member or
# unparseable record aborts the job, the pre-PR-7 behavior) or "salvage"
# (quarantine corrupt members/records, re-sync the record chain via the
# guesser machinery, finish the job with salvage.* counters reporting
# exactly what was lost).  Threaded spec/bgzf → io/bam → pipeline; the
# CLI's --errors flag sets it.
ERRORS_MODE = "hadoopbam.errors"
# A fault-injection plan spec (see hadoop_bam_tpu/faults/plan.py for the
# directive grammar).  Arms the process-global plan; the HBAM_FAULTS env
# var takes precedence (it covers subprocess drills).  Unset = disarmed,
# and the seams are zero-cost no-ops.
FAULTS_PLAN = "hadoopbam.faults.plan"
# Compressed-payload mesh shuffle (parallel/multihost.py): record bytes
# cross hosts as ≤64 KiB BGZF members (the Hadoop
# mapreduce.map.output.compress stance at ICI/NIC speed) — the sender
# re-blocks each destination's record run through the device deflate (or
# host zlib when the lanes tier declines), receivers inflate batched on
# the inflate lanes, and the memory budget's spill runs hold compressed
# members.  "false" selects the raw byte plane (plain size+body streams,
# the pre-PR-15 wire format); output is byte-identical either way.  The
# HBAM_SHUFFLE_COMPRESS env var covers subprocess workers.
SHUFFLE_COMPRESS = "hadoopbam.shuffle.compress"
# BGZF member payload size (bytes) for the shuffle re-block, clamped to
# the device codec cap (ops.flate.DEV_MAX_PAYLOAD, 57088 — a ≤64 KiB
# member on the wire).  Tests shrink it so interpret-mode lanes members
# stay ≤3 KiB; production leaves it at the cap.  HBAM_SHUFFLE_MEMBER_BYTES
# is the env twin.
SHUFFLE_MEMBER_BYTES = "hadoopbam.shuffle.member-bytes"
# Receiver-side parallel fetch pool width (Hadoop's parallel copier,
# mapreduce.reduce.shuffle.parallelcopies): this key → the
# HBAM_SHUFFLE_FETCH_THREADS env var → 8, capped at the peer count.
# The resolved value is surfaced in every host manifest.
SHUFFLE_FETCH_THREADS = "hadoopbam.shuffle.fetch-threads"
# Mesh observability plane (parallel/multihost.py): "true" arms every
# process's timeline tracer for the run, exports a per-host trace shard
# (trace-h<process_id>.json, clock-anchored at a dedicated barrier) plus
# a per-host manifest through the shuffle byte plane, and has process 0
# collect the shards into MESH_TRACE_DIR and fold the host manifests
# into a ClusterManifest (cluster_manifest.json).  The HBAM_MESH_TRACE /
# HBAM_MESH_TRACE_DIR env vars cover subprocess workers; unset =
# disarmed (zero mh.* trace events, byte-identical output).
# MESH_TRACE_DIR defaults to "<out_path>.mesh-trace".
MESH_TRACE = "hadoopbam.mesh.trace"
MESH_TRACE_DIR = "hadoopbam.mesh.trace-dir"
# Skew healing (parallel/multihost.py).  SKEW_BOUND: when the post-route
# output-row ratio max/mean exceeds this, the round refreshes its range
# partitioner from a per-host key reservoir (REPARTITION_SAMPLES keys
# per host, allgathered, re-cut at balanced quantiles) and re-routes —
# at most one refresh per round, counted as mh.repartition.*.  0
# disables the refresh.  SPECULATE_FACTOR: a host whose parts stage
# exceeds this multiple of the median peer duration at the
# parts-written barrier gets its stage re-executed by the fastest
# finished peer from the byte-plane locator; first finisher wins, the
# loser's parts are discarded by generation tag (mh.speculate.*).
# 0/unset disables speculation (the default — it trades redundant work
# for tail latency, Hadoop's mapreduce.map.speculative stance).
MESH_SKEW_BOUND = "hadoopbam.mesh.skew-bound"
MESH_SPECULATE_FACTOR = "hadoopbam.mesh.speculate-factor"
MESH_REPARTITION_SAMPLES = "hadoopbam.mesh.repartition-samples"
# Timeline tracer ring capacity (events) for ``--trace`` runs
# (utils/tracing.Tracer): the per-event buffer is bounded — on overflow
# the OLDEST events drop (counted in the export's ``dropped_events``)
# while the cumulative METRICS spans stay intact.  Unset = 65536.
TRACE_EVENTS = "hadoopbam.trace.events"
# ElasticExecutor hardening: wall-clock deadline per part-write attempt
# (milliseconds; 0/unset = no deadline — an attempt that exceeds it is
# counted failed and retried, Hadoop's task-timeout semantics) and the
# base backoff between retry attempts (milliseconds, doubled per attempt
# with deterministic jitter; default 50).
EXECUTOR_ATTEMPT_TIMEOUT_MS = "hadoopbam.executor.attempt-timeout-ms"
EXECUTOR_BACKOFF_MS = "hadoopbam.executor.backoff-ms"

_TRUE_WORDS = frozenset(("yes", "true", "t", "y", "1", "on", "enabled"))
_FALSE_WORDS = frozenset(("no", "false", "f", "n", "0", "off", "disabled"))


class Configuration:
    """A string-property map with the reference's lenient parsing semantics."""

    def __init__(self, props: Optional[Mapping[str, str]] = None) -> None:
        self._props: dict[str, str] = dict(props) if props else {}

    def set(self, key: str, value) -> None:
        self._props[key] = str(value)

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._props.get(key, default)

    def unset(self, key: str) -> None:
        self._props.pop(key, None)

    def __contains__(self, key: str) -> bool:
        return key in self._props

    def __iter__(self) -> Iterator[str]:
        return iter(self._props)

    def set_boolean(self, key: str, value: bool) -> None:
        self._props[key] = "true" if value else "false"

    def get_boolean(self, key: str, default: bool = False) -> bool:
        """Lenient boolean parse (reference util/ConfHelper.java:41-69):
        accepts yes/no, true/false, t/f, y/n, 1/0, on/off, enabled/disabled,
        case-insensitively; anything else falls back to the default."""
        raw = self._props.get(key)
        if raw is None:
            return default
        word = raw.strip().lower()
        if word in _TRUE_WORDS:
            return True
        if word in _FALSE_WORDS:
            return False
        return default

    def set_int(self, key: str, value: int) -> None:
        self._props[key] = str(value)

    def get_int(self, key: str, default: int = 0) -> int:
        raw = self._props.get(key)
        if raw is None:
            return default
        try:
            return int(raw.strip())
        except ValueError:
            return default

    def copy(self) -> "Configuration":
        return Configuration(self._props)
