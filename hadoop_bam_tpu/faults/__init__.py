"""Fault-injection arming: one process-global plan, read by every seam.

The seams (io/fs.py, spec/bgzf.py, ops/flate.py, parallel/executor.py,
serve/server.py) each check ``faults.ACTIVE is not None`` — a single
module-attribute read — before doing anything, so a disarmed process pays
no measurable cost and records no counters (the zero-overhead contract
tests/test_faults.py enforces).

Arming, in precedence order:

1. ``HBAM_FAULTS`` env var at import time (covers subprocesses — the
   ``kill -9`` drills arm their children this way);
2. the ``hadoopbam.faults.plan`` conf key via :func:`arm_from_conf`
   (the CLI's ``--faults`` and the daemon call it);
3. :func:`arm` directly from tests.
"""

from __future__ import annotations

import os
from typing import Optional, Union

from .plan import Directive, FaultPlan, InjectedResourceExhausted

__all__ = ["ACTIVE", "Directive", "FaultPlan", "InjectedResourceExhausted",
           "arm", "arm_from_conf", "arm_from_env", "disarm"]

#: The armed plan, or None (the common case — seams check this and stop).
ACTIVE: Optional[FaultPlan] = None


def arm(plan: Union[FaultPlan, str]) -> FaultPlan:
    """Arm a plan (or parse-and-arm a spec string) process-wide."""
    global ACTIVE
    ACTIVE = FaultPlan.parse(plan) if isinstance(plan, str) else plan
    return ACTIVE


def disarm() -> None:
    global ACTIVE
    ACTIVE = None


def arm_from_env() -> bool:
    """Arm from ``HBAM_FAULTS`` if set; returns whether a plan armed."""
    spec = os.environ.get("HBAM_FAULTS")
    if spec:
        arm(spec)
        return True
    return False


def arm_from_conf(conf) -> bool:
    """Arm from the ``hadoopbam.faults.plan`` conf key if present (and no
    env plan already armed — env wins so subprocess drills stay in
    control); returns whether a plan is armed after the call."""
    if ACTIVE is not None:
        return True
    from ..conf import FAULTS_PLAN

    spec = conf.get(FAULTS_PLAN) if conf is not None else None
    if spec:
        arm(spec)
        return True
    return False


arm_from_env()
