"""Deterministic fault-injection plans for the robustness seams.

The reference repo has no fault injection at all (SURVEY.md §5 calls the
gap out); its resilience story is whatever Hadoop's task retry happens to
exercise.  This module is the adversary the TPU build's fallbacks never
had: a seeded, declarative :class:`FaultPlan` that fires at the four seams
where real failures enter the pipeline —

- **byte I/O** (``io/fs.py`` reads): bit-flips, short reads, transient
  ``IOError``;
- **codec tiers** (``ops/flate.py`` wrappers + ``spec/bgzf.py`` host
  inflate): forced per-member tier-downs, detected payload corruption;
- **the part-write/executor boundary** (``parallel/executor.py``):
  attempt crashes, torn tmp files, injected latency, hard process death
  (the ``kill -9`` stand-in);
- **the serve socket** (``serve/server.py``): dropped connections,
  stalled replies.

A plan is a ``;``-separated list of directives, each
``site[:key=value[,key=value…]]``, e.g.::

    HBAM_FAULTS="seed=7;io.read.error:n=2;exec.crash:items=1,attempts=0"

Every directive carries ``n`` (how many times it fires; ``*`` =
unlimited) and site-specific filters.  Firing order is deterministic:
counters are consumed in call order and any randomness (bit positions)
comes from the plan's seeded RNG, so a given plan against a given
workload injects the same faults every run.  Offset-pinned bit-flips
(``io.read.bitflip:offset=…``) are *persistent* by default — a corrupt
disk byte is corrupt on every read, including margin-widened re-reads.

Directive reference:

===================  =====================================================
``seed=<int>``       RNG seed for seeded choices (bit positions).
``io.read.bitflip``  ``offset`` (absolute file offset; persistent unless
                     ``n`` given), ``bit`` (0-7), ``path`` (substring
                     filter), ``n``.
``io.read.short``    ``drop`` (bytes removed from the tail; default half
                     the read), ``path``, ``n``.
``io.read.error``    transient ``IOError``; ``path``, ``n``.
``flate.inflate.tierdown``  force members off the device inflate tiers;
                     ``members`` (match set), ``n``.
``flate.deflate.tierdown``  force members off the device deflate tiers;
                     ``members``, ``n``.
``flate.corrupt``    flip a byte of a host-inflated payload *before* the
                     CRC gate (detected corruption); ``n``.
``mh.corrupt``       flip a byte of a fetched mesh-shuffle BGZF member's
                     compressed payload *in flight* (receiver side, after
                     the wire, before inflate — the CRC gate catches it);
                     ``members`` (match set over the member index within
                     one fetched stream), ``n``.
``exec.crash``       raise inside an executor attempt; ``items``,
                     ``attempts`` (match sets), ``n``.
``exec.torn``        write a garbage tmp file, then raise (the torn-write
                     adversary for the atomic-rename contract); ``items``,
                     ``attempts``, ``n``.
``exec.delay``       sleep ``ms`` inside an attempt; ``items``,
                     ``attempts``, ``n``.  Also fired at the multihost
                     read stage with item = process id and attempt =
                     local split ordinal — ``items=1`` slows exactly
                     host 1, the mesh straggler drill
                     (tools/mesh_report.py must blame that host).
``exec.die``         ``os._exit(137)`` — SIGKILL's exit, mid-attempt (the
                     deterministic ``kill -9``); ``items``, ``attempts``,
                     ``n``.
``mh.speculate.lose``  delay the speculative re-execution of a straggling
                     host's parts stage just before its first-wins
                     promotion, forcing the speculative copy to lose the
                     race and be discarded cleanly (counted
                     ``mh.speculate.wasted_bytes``); ``ms`` (default
                     500), ``n``.
``serve.drop``       close the connection without replying; ``op``
                     (request-op filter), ``n``.
``serve.stall``      sleep ``ms`` before replying; ``op``, ``n``.
``arena.oom``        raise a device ``RESOURCE_EXHAUSTED`` stand-in at a
                     device-allocation seam (the lane batcher's shared
                     decode, the codec-tier launches) — drives the serve
                     layer's evict-retry-tierdown OOM path
                     deterministically; ``n``.
===================  =====================================================

Match sets: ``*`` (any), ``3``, ``0-2``, ``1,4,7``.

Zero cost when disarmed: the seams check one module global
(``faults.ACTIVE is None``) and touch no tracing counter — a clean
strict-mode run's metrics ledger is byte-identical with the subsystem
present (tests/test_faults.py asserts this).  When a directive fires it
counts ``faults.fired`` and ``faults.fired.<site>`` through METRICS so
injected runs are auditable.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional

from ..utils.tracing import METRICS

_SITES = frozenset(
    (
        "io.read.bitflip",
        "io.read.short",
        "io.read.error",
        "flate.inflate.tierdown",
        "flate.deflate.tierdown",
        "flate.corrupt",
        "mh.corrupt",
        "mh.speculate.lose",
        "exec.crash",
        "exec.torn",
        "exec.delay",
        "exec.die",
        "serve.drop",
        "serve.stall",
        "arena.oom",
    )
)
_UNLIMITED = -1


class InjectedResourceExhausted(MemoryError):
    """The ``arena.oom`` directive's device-OOM stand-in.

    Real device exhaustion surfaces as an ``XlaRuntimeError`` whose
    message carries ``RESOURCE_EXHAUSTED``; this class reproduces that
    shape (``utils.backend.is_resource_exhausted`` matches both), so the
    recovery path proven against the injection is the one a real OOM
    takes.
    """

    def __init__(self, site: str = "device"):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected device allocation failure "
            f"at {site} (arena.oom fault directive)"
        )


def _match(spec: Optional[str], value) -> bool:
    """Does ``value`` satisfy a match set (``*`` | n | a-b | a,b,c)?"""
    if spec is None or spec == "*":
        return True
    if value is None:
        return False
    v = int(value)
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part[1:]:  # allow negative singletons like -1
            lo, hi = part.split("-", 1) if not part.startswith("-") else (
                part[: part.index("-", 1)], part[part.index("-", 1) + 1:]
            )
            if int(lo) <= v <= int(hi):
                return True
        elif v == int(part):
            return True
    return False


class Directive:
    """One armed fault: a site, its filters, and a firing budget."""

    def __init__(self, site: str, params: Dict[str, str]):
        if site not in _SITES:
            raise ValueError(f"unknown fault site {site!r}")
        self.site = site
        self.params = params
        n = params.get("n")
        if n is None:
            # Offset-pinned bit-flips model a bad disk byte: persistent.
            persistent = site == "io.read.bitflip" and "offset" in params
            self.remaining = _UNLIMITED if persistent else 1
        else:
            self.remaining = _UNLIMITED if n == "*" else int(n)

    def int_param(self, key: str, default: int) -> int:
        raw = self.params.get(key)
        return default if raw is None else int(raw)

    def __repr__(self) -> str:  # readable failure logs
        return f"Directive({self.site}, {self.params}, n={self.remaining})"


class FaultPlan:
    """A seeded set of :class:`Directive`\\ s, consumed thread-safely."""

    def __init__(
        self, directives: List[Directive], seed: int = 0, spec: str = ""
    ):
        self.directives = directives
        self.seed = seed
        self.spec = spec
        self.rng = random.Random(seed)
        self._lock = threading.Lock()
        self.fired: Dict[str, int] = {}

    # -- construction -------------------------------------------------------

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        seed = 0
        directives: List[Directive] = []
        for raw in spec.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            if raw.startswith("seed="):
                seed = int(raw[5:])
                continue
            site, _, rest = raw.partition(":")
            params: Dict[str, str] = {}
            last_key: Optional[str] = None
            for kv in rest.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                if "=" in kv:
                    k, _, v = kv.partition("=")
                    last_key = k.strip()
                    params[last_key] = v.strip()
                elif last_key is not None:
                    # Continuation of a comma-holding match set, e.g.
                    # ``items=1,3,7`` — bare tokens extend the last value.
                    params[last_key] += "," + kv
                else:
                    raise ValueError(
                        f"bad fault directive parameter {kv!r} in {raw!r}"
                    )
            directives.append(Directive(site.strip(), params))
        return cls(directives, seed=seed, spec=spec)

    # -- firing core --------------------------------------------------------

    def _fire(self, site: str, **ctx) -> Optional[Directive]:
        """The first matching directive with budget left, consumed; counts
        ``faults.fired`` / ``faults.fired.<site>`` on a hit."""
        with self._lock:
            for d in self.directives:
                if d.site != site or d.remaining == 0:
                    continue
                if not self._matches(d, ctx):
                    continue
                if d.remaining != _UNLIMITED:
                    d.remaining -= 1
                self.fired[site] = self.fired.get(site, 0) + 1
                METRICS.count("faults.fired", 1)
                METRICS.count(f"faults.fired.{site}", 1)
                return d
        return None

    @staticmethod
    def _matches(d: Directive, ctx: Dict) -> bool:
        p = d.params
        if "path" in p and p["path"] not in str(ctx.get("path", "")):
            return False
        if "op" in p and p["op"] != "*" and ctx.get("op") != p["op"]:
            return False
        for key in ("items", "attempts", "members"):
            if key in p and not _match(p[key], ctx.get(key[:-1])):
                return False
        if "offset" in p:
            off = int(p["offset"])
            start = int(ctx.get("start", 0))
            if not (start <= off < start + int(ctx.get("length", 0))):
                return False
        return True

    # -- seam entry points --------------------------------------------------

    def io_read(self, path: str, start: int, data: bytes) -> bytes:
        """The byte-I/O seam: may raise a transient ``IOError`` or return
        corrupted/truncated bytes."""
        if self._fire("io.read.error", path=path, start=start,
                      length=len(data)) is not None:
            raise IOError(f"injected transient I/O error reading {path}")
        d = self._fire("io.read.short", path=path, start=start,
                       length=len(data))
        if d is not None and len(data):
            drop = min(d.int_param("drop", len(data) // 2), len(data))
            data = data[: len(data) - drop]
        d = self._fire("io.read.bitflip", path=path, start=start,
                       length=len(data))
        if d is not None and len(data):
            if "offset" in d.params:
                pos = int(d.params["offset"]) - start
            else:
                pos = self.rng.randrange(len(data))
            if 0 <= pos < len(data):
                bit = d.int_param("bit", 0) & 7
                flipped = bytearray(data)
                flipped[pos] ^= 1 << bit
                data = bytes(flipped)
        return data

    def flate_tierdown(self, kind: str, member: int) -> bool:
        """Force member ``member`` off the device ``kind`` ('inflate' /
        'deflate') tier, down to host zlib."""
        return self._fire(f"flate.{kind}.tierdown", member=member) is not None

    def corrupt_payload(self, payload: bytes) -> bytes:
        """Detected host-inflate corruption: flip one byte *before* the
        CRC gate, so the framing check — not luck — catches it."""
        if self._fire("flate.corrupt") is None or not payload:
            return payload
        pos = self.rng.randrange(len(payload))
        out = bytearray(payload)
        out[pos] ^= 0xFF
        return bytes(out)

    def mh_corrupt(self, member: int) -> bool:
        """The mesh-shuffle data-plane seam: should fetched shuffle
        member ``member`` be corrupted in flight?  The caller flips one
        byte of the member's *compressed* payload, so the BGZF CRC gate
        — not luck — catches it at inflate time (strict raises; salvage
        quarantines exactly that member)."""
        return self._fire("mh.corrupt", member=member) is not None

    def mh_speculate_lose(self) -> None:
        """The speculation-race seam: stall the speculative copy of a
        straggler's parts stage just before its first-wins promotion so
        the original wins the ``os.link`` race and the speculative
        output is discarded — the loser path exercised deterministically
        instead of by timing luck."""
        d = self._fire("mh.speculate.lose")
        if d is not None:
            time.sleep(d.int_param("ms", 500) / 1e3)

    def exec_attempt(self, item: int, attempt: int, tmp_path: str) -> None:
        """The executor seam: latency, torn tmp files, crashes, or hard
        process death, per (item, attempt).  The multihost read stage
        funnels through the same seam with (process id, split ordinal)
        so one directive grammar drives both the part-write drills and
        the mesh straggler/dead-host drills."""
        d = self._fire("exec.delay", item=item, attempt=attempt)
        if d is not None:
            time.sleep(d.int_param("ms", 100) / 1e3)
        if self._fire("exec.die", item=item, attempt=attempt) is not None:
            os._exit(137)  # SIGKILL's exit code: the kill -9 stand-in
        d = self._fire("exec.torn", item=item, attempt=attempt)
        if d is not None:
            with open(tmp_path, "wb") as f:
                f.write(b"\x00TORN\x00" * 64)
            raise IOError(
                f"injected torn write for item {item} attempt {attempt}"
            )
        if self._fire("exec.crash", item=item, attempt=attempt) is not None:
            raise RuntimeError(
                f"injected crash for item {item} attempt {attempt}"
            )

    def arena_oom(self, site: str = "device") -> bool:
        """The device-allocation seam: fire = raise-an-OOM-now.  Callers
        raise :class:`InjectedResourceExhausted` so the failure travels
        the exact path a real ``RESOURCE_EXHAUSTED`` would."""
        return self._fire("arena.oom", where=site) is not None

    def serve_action(self, op: Optional[str]) -> Optional[Dict]:
        """The serve-socket seam: ``{"action": "drop"}`` (close without a
        reply) or ``{"action": "stall", "ms": …}``, or None."""
        d = self._fire("serve.drop", op=op)
        if d is not None:
            return {"action": "drop"}
        d = self._fire("serve.stall", op=op)
        if d is not None:
            return {"action": "stall", "ms": d.int_param("ms", 200)}
        return None
